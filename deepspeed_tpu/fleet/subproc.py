"""Subprocess-backed replicas behind the in-process server protocol.

The lifecycle's ``factory(replica_id)`` normally builds an in-process
``LLMServer`` (one engine per replica — tier-1's shape). A real fleet
runs each replica in its OWN process; this module is that path without
changing a line of router/lifecycle code:

- :func:`worker_main` — the child: builds a server from a dotted
  ``module:callable`` factory, warms it (so ``hello`` implies warm),
  then serves newline-JSON ops (``submit`` / ``drain`` / ``halt``) on
  stdin and streams ``done`` completions on stdout.
- :class:`SubprocessReplica` — the parent-side proxy implementing the
  protocol surface the router and lifecycle touch: ``replica_id``,
  ``warmed``, ``error``, ``outstanding``, ``metrics``, ``submit``,
  ``start``/``drain``/``halt``/``steal_unfinished``, and ``_thread``
  (always None — the router's liveness checks treat a process with no
  engine thread as conclusively stopped, which for a killed child is
  exactly right).

Liveness rides the SAME beacon protocol as in-process replicas: pass
``heartbeat_dir`` and the CHILD writes ``FileHeartbeatTransport``
beacons — the parent router reads the shared directory, so a killed
child goes stale and the dead-replica takeover requeues its work with
no proxy-side special case.

Streaming tokens are not proxied (completions land whole); everything
the router's requeue/SLA machinery needs — tokens, finish reason,
latency stamps — is.

Run a worker directly:  ``python -m deepspeed_tpu.fleet.subproc \\
--factory pkg.mod:make_server --replica-id 3 --heartbeat-dir /tmp/hb``
"""

import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..serving.metrics import ServingMetrics
from ..serving.request import (FINISH_FAILED, Request, ServedResponse)
from ..serving.server import ServerClosed, ServerOverloaded
from ..utils.logging import logger

_ENC = dict(separators=(",", ":"))


def _send(stream, msg: Dict[str, Any]) -> None:
    stream.write(json.dumps(msg, **_ENC) + "\n")
    stream.flush()


# ---------------------------------------------------------------------------
# child side
# ---------------------------------------------------------------------------
def _resolve_factory(spec: str) -> Callable[[int], Any]:
    """``module.path:callable`` → the callable."""
    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(f"factory spec {spec!r} must be 'module:callable'")
    import importlib

    fn = importlib.import_module(mod_name)
    for part in attr.split("."):
        fn = getattr(fn, part)
    return fn


def worker_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="deepspeed_tpu.fleet.subproc")
    ap.add_argument("--factory", required=True,
                    help="module:callable building the LLMServer")
    ap.add_argument("--replica-id", type=int, required=True)
    ap.add_argument("--heartbeat-dir", default=None)
    args = ap.parse_args(argv)

    server = _resolve_factory(args.factory)(args.replica_id)
    if args.heartbeat_dir:
        from ..runtime.resilience.heartbeat import (FileHeartbeatTransport,
                                                    HeartbeatWriter)

        server.heartbeat = HeartbeatWriter(
            FileHeartbeatTransport(args.heartbeat_dir), rank=args.replica_id)
    # warm before hello: the parent's lifecycle treats hello as "warmed"
    from .lifecycle import ReplicaHandle

    handle = ReplicaHandle(args.replica_id, lambda rid: server)
    handle.spawn()
    report = handle.warm()
    server.start()
    out = sys.stdout
    _send(out, {"op": "hello", "replica_id": args.replica_id,
                "warm": report.to_params()})

    pending: Dict[int, Any] = {}
    lock = threading.Lock()

    def pump():
        while True:
            with lock:
                finished = [(i, r) for i, r in pending.items() if r.done]
                for i, _ in finished:
                    del pending[i]
            for i, resp in finished:
                _send(out, {"op": "done", "id": i,
                            "tokens": [int(t) for t in resp.tokens],
                            "reason": resp.finish_reason,
                            "ttft_s": resp.ttft_s, "e2e_s": resp.e2e_s})
            time.sleep(0.005)

    threading.Thread(target=pump, daemon=True, name="subproc-pump").start()

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        msg = json.loads(line)
        op = msg.get("op")
        if op == "submit":
            req = Request(np.asarray(msg["prompt"], np.int32),
                          max_new_tokens=int(msg.get("max_new_tokens", 64)),
                          eos_token_id=msg.get("eos_token_id"),
                          priority=int(msg.get("priority", 0)),
                          deadline_s=msg.get("deadline_s"),
                          request_id=msg.get("request_id"),
                          tenant=msg.get("tenant"))
            try:
                resp = server.submit(req, block=bool(msg.get("block", False)))
            except (ServerOverloaded, ServerClosed) as e:
                _send(out, {"op": "reject", "id": msg["id"],
                            "kind": type(e).__name__, "error": str(e)})
                continue
            with lock:
                pending[msg["id"]] = resp
        elif op == "drain":
            ok = server.drain(msg.get("timeout"))
            time.sleep(0.05)   # let the pump flush the last completions
            _send(out, {"op": "drained", "ok": bool(ok)})
            return 0
        elif op == "halt":
            server.halt()
            return 0
    server.halt()
    return 0


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------
class SubprocessReplica:
    """Router-protocol proxy for a replica living in a child process."""

    def __init__(self, replica_id: int, factory_spec: str, *,
                 heartbeat_dir: Optional[str] = None,
                 python: Optional[str] = None,
                 hello_timeout_s: float = 600.0,
                 clock: Callable[[], float] = time.monotonic):
        self.replica_id = int(replica_id)
        self.clock = clock
        self.metrics = ServingMetrics(clock=clock)
        self.heartbeat = None       # the CHILD beats; the router only reads
        self.error: Optional[BaseException] = None
        self.warmed = False
        self.fused_decode_chunk = 0   # tuned child-side during its warm
        self.warm_params: Dict[str, str] = {}
        self._thread = None         # no parent-side engine thread, ever
        self._accepting = True
        self._lock = threading.Lock()
        self._pending: Dict[int, ServedResponse] = {}
        self._next_id = 0
        cmd = [python or sys.executable, "-m", "deepspeed_tpu.fleet.subproc",
               "--factory", factory_spec, "--replica-id", str(self.replica_id)]
        if heartbeat_dir:
            cmd += ["--heartbeat-dir", heartbeat_dir]
        self.proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE, text=True,
                                     bufsize=1, env=dict(os.environ))
        self._hello = threading.Event()
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"subproc-read-{replica_id}")
        self._reader.start()
        if not self._hello.wait(hello_timeout_s):
            self.proc.kill()
            raise RuntimeError(f"subprocess replica {replica_id}: no hello "
                               f"within {hello_timeout_s}s")

    # -- protocol surface ---------------------------------------------------
    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self._pending)

    def start(self):
        return self

    def submit(self, request: Request, *, block: bool = False,
               timeout: Optional[float] = None,
               _response: Optional[ServedResponse] = None) -> ServedResponse:
        with self._lock:
            if not self._accepting or self.proc.poll() is not None:
                raise ServerClosed(
                    f"subprocess replica {self.replica_id} is not accepting")
            mid = self._next_id
            self._next_id += 1
            if _response is None:
                resp = ServedResponse(request, mid, self.clock())
            else:
                resp = _response
                resp.uid = mid
                self.metrics.requeues += 1
            resp.replica_id = self.replica_id
            self._pending[mid] = resp
        try:
            _send(self.proc.stdin, {
                "op": "submit", "id": mid,
                "prompt": [int(t) for t in resp.engine_prompt()],
                "max_new_tokens": resp.remaining_new_tokens(),
                "eos_token_id": request.eos_token_id,
                "priority": request.priority,
                "deadline_s": request.deadline_s,
                "request_id": request.request_id,
                "tenant": getattr(request, "tenant", None),
                "block": bool(block),
            })
        except (BrokenPipeError, OSError) as e:
            with self._lock:
                self._pending.pop(mid, None)
            raise ServerClosed(
                f"subprocess replica {self.replica_id} pipe closed") from e
        self.metrics.on_submit(resp)
        return resp

    def _read_loop(self) -> None:
        try:
            for line in self.proc.stdout:
                msg = json.loads(line)
                op = msg.get("op")
                if op == "hello":
                    self.warm_params = msg.get("warm", {})
                    self.warmed = True
                    self._hello.set()
                elif op == "done":
                    self._on_done(msg)
                elif op == "reject":
                    self._on_reject(msg)
                elif op == "drained":
                    self._drained = bool(msg.get("ok"))
        except Exception as e:  # noqa: BLE001 - a dead pipe ends the loop
            logger.warning(f"fleet: subprocess replica {self.replica_id} "
                           f"reader stopped: {e!r}")

    def _on_done(self, msg: Dict[str, Any]) -> None:
        with self._lock:
            resp = self._pending.pop(msg["id"], None)
        if resp is None or resp.done:
            return
        now = self.clock()
        # replay the child's lifecycle onto the handle; latency stamps are
        # reconstructed so ttft_s/e2e_s read the child's own measurements
        for tok in msg.get("tokens", [])[len(resp.tokens):]:
            resp._on_token(int(tok), now)
        if msg.get("ttft_s") is not None and resp.tokens:
            resp.first_token_time = resp.arrival_time + float(msg["ttft_s"])
        resp._on_finish(msg.get("reason") or FINISH_FAILED, now)
        if msg.get("e2e_s") is not None:
            resp.finish_time = resp.arrival_time + float(msg["e2e_s"])
        self.metrics.on_finish(resp)

    def _on_reject(self, msg: Dict[str, Any]) -> None:
        with self._lock:
            resp = self._pending.pop(msg["id"], None)
        if resp is not None and not resp.done:
            self.metrics.on_reject(resp)
            resp._on_finish(FINISH_FAILED, self.clock())
            self.metrics.on_finish(resp)

    # -- lifecycle ----------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            self._accepting = False
        try:
            _send(self.proc.stdin, {"op": "drain", "timeout": timeout})
        except (BrokenPipeError, OSError):
            return False
        try:
            self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return False
        deadline = self.clock() + 5.0
        while self.outstanding and self.clock() < deadline:
            time.sleep(0.01)    # reader thread is landing the last dones
        return not self.outstanding

    def halt(self) -> None:
        with self._lock:
            self._accepting = False
        try:
            _send(self.proc.stdin, {"op": "halt"})
            self.proc.wait(2.0)
        except Exception:
            pass  # swallow-ok: an unresponsive child is killed below
        if self.proc.poll() is None:
            self.proc.kill()

    def steal_unfinished(self) -> List[ServedResponse]:
        if self.proc.poll() is None:
            raise RuntimeError("steal_unfinished on a live subprocess "
                               "replica (halt() it first)")
        with self._lock:
            out = [r for r in self._pending.values() if not r.done]
            self._pending.clear()
        return out

    def __repr__(self):  # pragma: no cover - debugging aid
        alive = self.proc.poll() is None
        return (f"SubprocessReplica(replica={self.replica_id}, "
                f"alive={alive}, outstanding={self.outstanding})")


if __name__ == "__main__":   # pragma: no cover - exercised via subprocess
    sys.exit(worker_main())
