"""Importable server factories for subprocess-replica tests and drills.

``SubprocessReplica`` launches ``python -m deepspeed_tpu.fleet.subproc
--factory module:callable`` — the factory must be importable from a fresh
interpreter, so it cannot live in a pytest module. This is that module:
one tiny CPU-sized server, matching the serving test fixtures.
"""


def make_tiny_server(replica_id: int):
    """A serving-test-sized LLMServer (2-layer toy model, 64 KV blocks)."""
    import jax
    import jax.numpy as jnp

    from ..inference.v2 import InferenceEngineV2, RaggedInferenceEngineConfig
    from ..models.transformer import TransformerConfig, TransformerLM
    from ..serving.server import LLMServer

    cfg = TransformerConfig(vocab_size=97, hidden_size=48,
                            intermediate_size=96, num_layers=2, num_heads=4,
                            num_kv_heads=2, max_seq_len=128,
                            dtype=jnp.float32, norm="rmsnorm",
                            activation="swiglu")
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=16, max_ragged_sequence_count=4, max_chunk_size=8,
        num_kv_blocks=64, kv_block_size=8, max_blocks_per_seq=8,
        dtype="float32"))
    return LLMServer(engine, replica_id=replica_id)
