"""Replica lifecycle: SPAWNING → WARMING → JOINED → DRAINING → DEAD.

Reference shape: DeepSpeed-MII's deployment tier brings replicas up
*behind* the load balancer — a replica takes traffic only after its
engine exists and its programs are compiled. This module is that
contract for the TPU serving tier: a :class:`ReplicaHandle` walks one
replica through the state machine, and the router's warm gate
(``ReplicaRouter.add_replica(ready=...)``) guarantees no dispatch ever
lands on a replica that has not finished WARMING.

The warm step is where the repo's two caches pay off (the fleet half of
the ROADMAP north star):

comm-plan cache
    a :class:`~deepspeed_tpu.comm.planner.CollectivePlanner` configured
    in this process loaded its per-``MeshFingerprint`` plan at
    construction; the warm report records how many decisions came from
    cache vs. were searched, and the microbench ``probe_stats`` delta
    across the warm proves no new probe programs were built.

autotune winner cache
    the serving knob the fleet actually tunes per mesh —
    ``fused_decode_chunk`` — goes through the Autotuner-v2
    :class:`~deepspeed_tpu.control.winners.WinnerCache`: the FIRST
    replica on a mesh probes the candidate chunks once (timed decode
    bursts on its own warm engine, before it joins) and stores the
    winner; every LATER replica applies the recorded winner with ZERO
    probes. ``WarmReport.zero_probe_join()`` is the assertion the fs
    bench rung and the warm-join test check.

A handle is deliberately supervisor-agnostic: :class:`FleetManager`
(manager.py) owns policy (when to scale), ledger entries, and reaping;
the handle owns mechanism (how one replica moves between states).
"""

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime.resilience.chaos import get_chaos
from ..utils.logging import logger

# -- states -----------------------------------------------------------------
SPAWNING = "SPAWNING"   # handle created; engine/server being constructed
WARMING = "WARMING"     # server exists; compiling + applying cached winners
JOINED = "JOINED"       # registered with the router, taking traffic
DRAINING = "DRAINING"   # dispatch stopped, in-flight work finishing
DEAD = "DEAD"           # gone (drained, reaped, or killed)

STATES = (SPAWNING, WARMING, JOINED, DRAINING, DEAD)

#: legal transitions; DEAD is reachable from everywhere (reap/kill)
_TRANSITIONS = {
    SPAWNING: (WARMING, DEAD),
    WARMING: (JOINED, DEAD),
    JOINED: (DRAINING, DEAD),
    DRAINING: (DEAD,),
    DEAD: (),
}

#: the serving search space the fleet tunes per mesh (Autotuner-v2
#: vocabulary: dimension -> candidate names; the winner's overrides carry
#: the resolved chunk). One dimension today — the fused-decode chunk —
#: because it is the one serving knob with a real per-mesh answer.
SERVING_SPACE_DIMS: Dict[str, List[str]] = {
    "fused_decode_chunk": ["fd0", "fd8"],
}
SERVING_SPACE_METRIC = "serving_decode_tok_s"
_CHUNK_OF = {"fd0": 0, "fd8": 8}


class ReplicaSpawnError(RuntimeError):
    """Replica bring-up failed before the server existed (host allocation,
    process launch, or the ``replica_spawn_fail`` chaos drill)."""


def serving_space_signature() -> str:
    from ..control.winners import space_signature

    return space_signature(SERVING_SPACE_DIMS, SERVING_SPACE_METRIC)


@dataclass
class WarmReport:
    """What one replica's warm-up actually did — the evidence the
    zero-probe join contract is judged by (ledger params, bench asserts)."""
    replica_id: int = -1
    warm_s: float = 0.0
    warm_tokens: int = 0
    # comm-plan cache: decisions present on the planner after warm, and
    # how many of them were loaded from the per-mesh plan cache
    plan_decisions: int = 0
    plan_from_cache: int = 0
    # microbench probe programs BUILT during this warm (cache-hit lookups
    # don't count) — 0 is the zero-probe contract for the plan side
    probes_built: int = 0
    # autotune winner cache: did the serving winner come from cache, and
    # how many timed probe runs did THIS replica execute (0 on a hit)
    autotune_from_cache: bool = False
    autotune_probes: int = 0
    winner_name: Optional[str] = None
    fused_decode_chunk: Optional[int] = None

    def zero_probe_join(self) -> bool:
        """True when this replica joined without running a single probe:
        no microbench programs built, no autotune candidates timed."""
        return self.probes_built == 0 and self.autotune_probes == 0

    def to_params(self) -> Dict[str, str]:
        """Ledger-friendly (str->str) rendering for ControlLedger params."""
        return {
            "replica": str(self.replica_id),
            "warm_s": f"{self.warm_s:.3f}",
            "warm_tokens": str(self.warm_tokens),
            "plan_decisions": str(self.plan_decisions),
            "plan_from_cache": str(self.plan_from_cache),
            "probes_built": str(self.probes_built),
            "autotune_from_cache": str(self.autotune_from_cache),
            "autotune_probes": str(self.autotune_probes),
            "winner": str(self.winner_name),
            "fused_decode_chunk": str(self.fused_decode_chunk),
            "zero_probe": str(self.zero_probe_join()),
        }


class ReplicaHandle:
    """One replica's walk through the lifecycle state machine.

    ``factory(replica_id)`` builds the replica's ``LLMServer`` (the
    in-process path; a subprocess-backed server that speaks the same
    protocol — see :mod:`.subproc` — drops in unchanged, which is what
    keeps the state machine honest about real deployments). The handle
    never starts the server itself: joining the router does, so a replica
    that fails to warm never has an engine thread to leak."""

    def __init__(self, replica_id: int, factory: Callable[[int], Any], *,
                 warm_prompt_tokens: int = 8, warm_new_tokens: int = 8,
                 probe_new_tokens: int = 8,
                 autotune_cache_dir: Optional[str] = None,
                 use_winner_cache: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.replica_id = int(replica_id)
        self.factory = factory
        self.warm_prompt_tokens = int(warm_prompt_tokens)
        self.warm_new_tokens = int(warm_new_tokens)
        self.probe_new_tokens = int(probe_new_tokens)
        self.autotune_cache_dir = autotune_cache_dir
        self.use_winner_cache = bool(use_winner_cache)
        self.clock = clock
        self.server: Optional[Any] = None
        self.report = WarmReport(replica_id=self.replica_id)
        self.state = SPAWNING
        self.transitions: List[Tuple[str, float]] = [(SPAWNING, clock())]

    # -- state machine ------------------------------------------------------
    def _set_state(self, new: str) -> None:
        if new not in _TRANSITIONS[self.state] and new != self.state:
            raise RuntimeError(f"replica {self.replica_id}: illegal "
                               f"transition {self.state} -> {new}")
        if new != self.state:
            self.state = new
            self.transitions.append((new, self.clock()))
            logger.info(f"fleet: replica {self.replica_id} -> {new}")

    @property
    def site(self) -> str:
        """Chaos site name — the same ``replicaN`` vocabulary the serving
        drills (``replica_kill``/``slow_prefill``) use."""
        return f"replica{self.replica_id}"

    # -- SPAWNING -----------------------------------------------------------
    def spawn(self) -> Any:
        """Build the server (engine construction included). The
        ``replica_spawn_fail`` drill fires HERE — before the server exists
        — modeling a host/process allocation failure; the caller
        (FleetManager) must reap the handle, and the router must never
        have seen this replica."""
        assert self.state == SPAWNING, f"spawn() in state {self.state}"
        chaos = get_chaos()
        if chaos is not None and chaos.fire("replica_spawn_fail", self.site):
            self._set_state(DEAD)
            raise ReplicaSpawnError(
                f"chaos: replica {self.replica_id} spawn failed")
        try:
            self.server = self.factory(self.replica_id)
        except BaseException:
            self._set_state(DEAD)
            raise
        if getattr(self.server, "replica_id", self.replica_id) != self.replica_id:
            srv_rid = self.server.replica_id
            self._set_state(DEAD)
            raise ReplicaSpawnError(
                f"factory built replica_id={srv_rid}, "
                f"handle is {self.replica_id}")
        self._set_state(WARMING)
        return self.server

    # -- WARMING ------------------------------------------------------------
    def warm(self) -> WarmReport:
        """Compile the engine's programs and apply the cached per-mesh
        winners, so the JOIN is probe-free and the first real request
        never pays a compile. Runs on the caller's thread against the
        not-yet-started server's engine (single-threaded by construction:
        the engine thread only exists after join)."""
        assert self.state == WARMING, f"warm() in state {self.state}"
        chaos = get_chaos()
        if chaos is not None:
            stall = chaos.value("replica_slow_warm", self.site)
            if stall:
                # slow-warm drill: bring-up stalls (a cold cache fill, a
                # slow compile) — the warm gate must keep traffic off this
                # replica for the whole stall, not just until add_replica
                logger.warning(f"chaos: replica {self.replica_id} warm "
                               f"stalled {float(stall):.3f}s")
                time.sleep(float(stall))
        t0 = self.clock()
        try:
            from ..comm.planner.microbench import probe_stats

            probes_before = probe_stats().get("built", 0)
        except Exception:
            probes_before = None
        self._apply_winner()
        self._warm_generate()
        try:
            from ..comm.planner.microbench import probe_stats

            if probes_before is not None:
                self.report.probes_built = (probe_stats().get("built", 0)
                                            - probes_before)
        except Exception:
            pass
        self._record_plan_stats()
        self.report.warm_s = self.clock() - t0
        # the server is warm by fiat of this completed warm-up — the
        # router's gate (and its lazy promotion) reads this flag
        self.server.warmed = True
        return self.report

    def _warm_prompt(self) -> np.ndarray:
        """Deterministic tiny prompt inside any model's vocab (token ids
        1..N — 0 is conventionally a pad/special id)."""
        return (np.arange(self.warm_prompt_tokens, dtype=np.int32) % 32) + 1

    def _warm_generate(self) -> None:
        """One short generation through the server's own engine: compiles
        the packed SplitFuse step and — when a fused chunk was resolved —
        the fused decode path, exactly the programs real traffic runs."""
        engine = getattr(self.server, "engine", None)
        if engine is None or not hasattr(engine, "generate"):
            return      # protocol server (e.g. subprocess proxy): the
                        # remote side warms itself before reporting warm
        out = engine.generate([self._warm_prompt()],
                              max_new_tokens=self.warm_new_tokens)
        self.report.warm_tokens += sum(len(t) for t in out)
        chunk = getattr(self.server, "fused_decode_chunk", 0)
        if chunk and chunk > 1 and hasattr(engine, "decode_batch"):
            # compile the fused path at its real chunk size too
            self.report.warm_tokens += self._run_decode(engine, chunk,
                                                        self.warm_new_tokens)

    def _run_decode(self, engine, chunk: int, new_tokens: int) -> int:
        """Prefill one probe sequence, then decode ``new_tokens`` via the
        requested path (fused chunks when ``chunk > 1``, packed
        single-token steps otherwise). Returns tokens generated."""
        uid = 1_000_000 + self.replica_id
        engine.put([uid], [self._warm_prompt()], max_new_tokens=new_tokens)
        while any(s.in_prefill for s in engine.state_manager.all()
                  if not s.done):
            engine.step()
            if engine.last_num_scheduled == 0:
                break
        produced = 0
        while True:
            seq = engine.state_manager.get(uid)
            if seq is None or seq.done or produced >= new_tokens:
                break
            if chunk > 1 and hasattr(engine, "decode_batch"):
                out = engine.decode_batch(min(chunk, new_tokens - produced))
                produced += sum(len(t) for t in (out or {}).values())
                if not out:
                    break
            else:
                out = engine.step()
                produced += len(out or {})
                if engine.last_num_scheduled == 0 and not out:
                    break
        engine.flush(uid)
        return produced

    def _apply_winner(self) -> None:
        """Autotuner-v2 winner application: a cache hit applies the
        recorded ``fused_decode_chunk`` with zero probes; a miss (first
        replica on this mesh) times each candidate once on THIS replica's
        warm engine and stores the winner for the rest of the fleet."""
        if not self.use_winner_cache:
            return
        engine = getattr(self.server, "engine", None)
        if engine is None or not hasattr(self.server, "fused_decode_chunk"):
            return
        try:
            from ..comm.planner.topo import MeshFingerprint
            from ..control.winners import WinnerCache

            fp = MeshFingerprint.capture()
            cache = WinnerCache(self.autotune_cache_dir)
            sig = serving_space_signature()
            hit = cache.lookup(fp, sig)
        except Exception as e:
            logger.warning(f"fleet: winner cache unavailable "
                           f"({e!r}); keeping configured knobs")
            return
        if hit is not None:
            chunk = hit.get("overrides", {}).get("fused_decode_chunk")
            if chunk is not None:
                self.server.fused_decode_chunk = int(chunk)
                self.report.fused_decode_chunk = int(chunk)
            self.report.winner_name = hit.get("winner")
            self.report.autotune_from_cache = True
            logger.info(f"fleet: replica {self.replica_id} applied cached "
                        f"serving winner {hit.get('winner')!r} "
                        f"(fused_decode_chunk={chunk}) — zero probes")
            return
        # miss: probe once, on the warm engine, BEFORE taking traffic
        timings: Dict[str, float] = {}
        for name in SERVING_SPACE_DIMS["fused_decode_chunk"]:
            chunk = _CHUNK_OF[name]
            self._run_decode(engine, chunk, self.probe_new_tokens)  # compile
            t0 = self.clock()
            produced = self._run_decode(engine, chunk, self.probe_new_tokens)
            dt = max(1e-9, self.clock() - t0)
            timings[name] = produced / dt
            self.report.autotune_probes += 1
        winner = max(timings, key=lambda k: timings[k])
        chunk = _CHUNK_OF[winner]
        self.server.fused_decode_chunk = chunk
        self.report.winner_name = winner
        self.report.fused_decode_chunk = chunk
        try:
            cache.store(fp, sig, {
                "winner": winner,
                "overrides": {"fused_decode_chunk": chunk},
                "timings_tok_s": {k: round(v, 2) for k, v in timings.items()},
                "probes_run": self.report.autotune_probes,
                "metric": SERVING_SPACE_METRIC,
            })
        except OSError:
            pass  # read-only FS: winner still applies in-memory
        logger.info(f"fleet: replica {self.replica_id} probed serving "
                    f"winner {winner!r} ({timings}) and cached it")

    def _record_plan_stats(self) -> None:
        try:
            from ..comm.planner import get_planner, planner_active

            if planner_active():
                pl = get_planner()
                decisions = set(getattr(pl.plan, "decisions", {}) or {})
                self.report.plan_decisions = len(decisions)
                self.report.plan_from_cache = len(
                    decisions & set(getattr(pl, "_from_cache", ())))
        except Exception:
            pass  # no planner in this process: plan stats stay zero

    # -- JOINED -------------------------------------------------------------
    def join(self, router) -> None:
        """Register with the router. The server is warm, so the router's
        gate admits it immediately (``ready`` inferred from ``warmed``) —
        this is the FIRST moment traffic can reach the replica."""
        assert self.state == WARMING, f"join() in state {self.state}"
        router.add_replica(self.server)
        self._set_state(JOINED)

    def bring_up(self, router) -> WarmReport:
        """spawn → warm → join, the full scale-out arc."""
        self.spawn()
        self.warm()
        self.join(router)
        return self.report

    # -- DRAINING / DEAD ----------------------------------------------------
    def drain(self, router=None, timeout: Optional[float] = None) -> bool:
        """Graceful exit: stop dispatch, finish in-flight work, stop."""
        self._set_state(DRAINING)
        if router is not None:
            ok = router.drain_replica(self.replica_id, timeout)
        else:
            ok = self.server.drain(timeout) if self.server is not None else True
        self._set_state(DEAD)
        return ok

    def kill(self) -> None:
        """Abrupt stop (reap path, chaos cleanup): halt whatever exists."""
        if self.server is not None:
            try:
                self.server.halt()
            except Exception:
                pass  # swallow-ok: reaping a half-built server must not throw over its corpse
        if self.state != DEAD:
            self.state = DEAD          # kill is legal from every state
            self.transitions.append((DEAD, self.clock()))

    def describe(self) -> Dict[str, Any]:
        return {"replica": self.replica_id, "state": self.state,
                "transitions": [(s, round(t, 3)) for s, t in self.transitions],
                "warm": self.report.to_params()}

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"ReplicaHandle(replica={self.replica_id}, state={self.state})"
