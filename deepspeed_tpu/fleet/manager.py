"""FleetManager: the supervisor's actual elastic-scaling actuator.

PR 12's control plane can *decide* ``serving_scale`` — ``rule_sla``
fires its registered ``scale_fn`` — but until now nothing in the tree
could actually spawn, warm, join, or drain a replica. The manager is
that actuator:

scale-out (SLA pressure)
    ``manager.scale_out`` IS the ``scale_fn``: attach it via
    ``supervisor.attach_server(server, scale_fn=manager.scale_out)``.
    It walks a fresh :class:`~.lifecycle.ReplicaHandle` through
    spawn → warm → join, so by the time the router can dispatch to the
    new replica its programs are compiled and the cached per-mesh
    winners applied (zero probes — see lifecycle.py). The ledger entry
    ``replica_join`` carries the full :class:`~.lifecycle.WarmReport`.

reap on failure
    if bring-up fails ANYWHERE (the ``replica_spawn_fail`` drill, an
    engine OOM mid-warm, a factory bug), the manager reaps: halts
    whatever half-exists, removes any router registration, marks the
    handle DEAD, records ``replica_reap`` — and re-raises, so
    ``rule_sla``'s existing fallback (record ``failed:<type>``, shed)
    still runs. A failed scale-out never leaks a WARMING entry in the
    router and never strands an engine thread.

scale-in (sustained under-utilization)
    ``manager.poll()`` (call it from the serving poll loop) watches the
    fleet's mean outstanding-per-replica; when it sits below
    ``scale_in_low_watermark`` with more than ``min_replicas`` joined,
    the ``fleet_scale_in`` rule fires through the SAME
    :class:`~deepspeed_tpu.control.guard.FlapGuard` hysteresis/cooldown/
    budget as every other control action, and the LEAST-loaded replica
    drains gracefully (``serving_scale_in`` in the ledger).

Every transition is a ControlLedger entry, so fleet history rides the
registry, the monitor bridge, flight dumps, and the doctor's
supervisor-action evidence for free.
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger
from .lifecycle import DEAD, JOINED, ReplicaHandle


class FleetAtCapacity(RuntimeError):
    """scale_out at max_replicas — rule_sla's fallback (shedding) applies."""


class FleetManager:
    def __init__(self, factory: Callable[[int], Any], *,
                 router=None, supervisor=None, ledger=None, guard=None,
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_in_low_watermark: float = 0.5,
                 drain_timeout_s: float = 60.0,
                 autotune_cache_dir: Optional[str] = None,
                 warm_kwargs: Optional[Dict[str, Any]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.factory = factory
        self.router = router
        self.supervisor = supervisor
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_in_low_watermark = float(scale_in_low_watermark)
        self.drain_timeout_s = drain_timeout_s
        self.autotune_cache_dir = autotune_cache_dir
        self.warm_kwargs = dict(warm_kwargs or {})
        self.clock = clock
        self.handles: Dict[int, ReplicaHandle] = {}
        self._next_rid = 0
        # scale operations are serialized: two SLA ticks firing scale_out
        # concurrently must not both spawn (the guard's cooldown usually
        # prevents it, but the manager must be safe without it)
        self._scale_lock = threading.Lock()
        if ledger is not None:
            self.ledger = ledger
        elif supervisor is not None:
            self.ledger = supervisor.ledger
        else:
            from ..control.ledger import ControlLedger

            self.ledger = ControlLedger()
        if guard is not None:
            self.guard = guard
        elif supervisor is not None:
            self.guard = supervisor.guard
        else:
            from ..control.guard import FlapGuard

            self.guard = FlapGuard(clock=clock)

    # -- bring-up -----------------------------------------------------------
    def _new_handle(self) -> ReplicaHandle:
        rid = self._next_rid
        self._next_rid += 1
        return ReplicaHandle(rid, self.factory,
                             autotune_cache_dir=self.autotune_cache_dir,
                             clock=self.clock, **self.warm_kwargs)

    def start(self, n: int, *, transport=None, dead_after_s: float = 10.0,
              router_kwargs: Optional[Dict[str, Any]] = None):
        """Bring up the initial fleet: spawn+warm ``n`` replicas, build the
        router over them, record their JOINED handles. Returns the router
        (also stored on the manager)."""
        from ..serving.replica import ReplicaRouter

        if self.router is not None:
            raise RuntimeError("fleet already started")
        handles = []
        for _ in range(max(1, int(n))):
            h = self._new_handle()
            try:
                h.spawn()
                h.warm()
            except BaseException:
                self.handles[h.replica_id] = h
                self._reap(h, during="start")
                for prev in handles:    # a failed day-one bring-up is fatal;
                    prev.kill()         # don't leak the siblings' threads
                raise
            handles.append(h)
        kw = dict(router_kwargs or {})
        if transport is not None:
            kw.setdefault("transport", transport)
            kw.setdefault("dead_after_s", dead_after_s)
        self.router = ReplicaRouter([h.server for h in handles],
                                    **kw).start()
        for h in handles:
            # constructor-registered: flip the handle to JOINED directly
            h._set_state(JOINED)
            self.handles[h.replica_id] = h
            self.ledger.record(
                "replica_join", step=0, rule="fleet_start",
                signal=f"initial fleet bring-up ({n} replica(s))",
                reason=f"replica {h.replica_id} warmed and joined",
                params=h.report.to_params())
        return self.router

    # -- scale-out (the supervisor's scale_fn) ------------------------------
    def scale_out(self, sup=None) -> int:
        """Spawn → warm → join one replica; returns its id (rule_sla's
        ledger entry stringifies it as ``added``). Raises on failure AFTER
        reaping, so the SLA rule's shed fallback still engages."""
        with self._scale_lock:
            if self.router is None:
                raise RuntimeError("fleet not started (no router)")
            joined = self._joined()
            if len(joined) >= self.max_replicas:
                raise FleetAtCapacity(
                    f"fleet already at max_replicas={self.max_replicas}")
            handle = self._new_handle()
            self.handles[handle.replica_id] = handle
            step = self._step()
            try:
                report = handle.bring_up(self.router)
            except BaseException as e:
                self._reap(handle, during="scale_out", error=e)
                raise
            how = ("cached winners, zero probes"
                   if report.zero_probe_join() else "probed winners")
            self.ledger.record(
                "replica_join", step=step, rule="fleet_scale_out",
                signal=f"fleet {len(joined)} -> {len(joined) + 1} replica(s)",
                reason=f"replica {handle.replica_id} warmed and joined "
                       f"({how})",
                params=report.to_params())
            logger.info(f"fleet: scaled out to replica {handle.replica_id} "
                        f"(zero_probe={report.zero_probe_join()})")
            return handle.replica_id

    def _reap(self, handle: ReplicaHandle, *, during: str,
              error: Optional[BaseException] = None) -> None:
        """Satellite-6 contract: a failed bring-up leaves NOTHING behind —
        no WARMING entry in the router, no orphan engine thread, no handle
        stuck mid-state. Always records ``replica_reap``."""
        rid = handle.replica_id
        if self.router is not None and rid in getattr(self.router,
                                                      "replicas", {}):
            try:
                self.router.remove_replica(rid)   # also halts the server
            except RuntimeError:
                # it carries tracked work (join succeeded, failure came
                # later): drain instead of stranding its clients
                self.router.drain_replica(rid, self.drain_timeout_s)
        handle.kill()
        self.ledger.record(
            "replica_reap", step=self._step(), rule=f"fleet_{during}",
            signal=f"replica {rid} bring-up failed during {during}",
            reason=f"reaped half-spawned replica {rid}: "
                   f"{type(error).__name__ if error else 'error'}"
                   f"{f': {error}' if error else ''}",
            outcome=f"failed:{type(error).__name__}" if error else "ok")
        logger.warning(f"fleet: reaped replica {rid} after failed {during}")

    # -- scale-in -----------------------------------------------------------
    def poll(self, step: Optional[int] = None) -> Optional[int]:
        """One under-utilization observation; drains the least-loaded
        replica when the ``fleet_scale_in`` rule fires (flap-guarded).
        Returns the drained replica id, or None. Call this from the same
        loop that calls ``router.check()``."""
        if self.router is None:
            return None
        self._reconcile_dead()
        joined = self._joined()
        can_shrink = len(joined) > self.min_replicas
        load = (sum(h.server.outstanding for h in joined) / len(joined)
                if joined else 0.0)
        asserted = can_shrink and load < self.scale_in_low_watermark
        if not self.guard.should_fire("fleet_scale_in", asserted):
            return None
        victim = min(joined, key=lambda h: (h.server.outstanding,
                                            h.replica_id))
        return self.scale_in(victim.replica_id, step=step,
                             signal=f"mean outstanding {load:.2f} < "
                                    f"{self.scale_in_low_watermark:g} across "
                                    f"{len(joined)} replica(s)")

    def scale_in(self, rid: Optional[int] = None, *, step: Optional[int] = None,
                 signal: str = "operator request") -> Optional[int]:
        """Drain one JOINED replica (least-loaded when ``rid`` is None)."""
        with self._scale_lock:
            joined = self._joined()
            if not joined:
                return None
            if rid is None:
                handle = min(joined, key=lambda h: (h.server.outstanding,
                                                    h.replica_id))
            else:
                handle = self.handles[rid]
                if handle.state != JOINED:
                    raise RuntimeError(f"replica {rid} is {handle.state}, "
                                       f"not {JOINED}")
            ok = handle.drain(self.router, self.drain_timeout_s)
            self.ledger.record(
                "serving_scale_in", step=step if step is not None
                else self._step(),
                rule="fleet_scale_in", signal=signal,
                reason=f"drained least-loaded replica {handle.replica_id}",
                params={"replica": str(handle.replica_id),
                        "drained_clean": str(bool(ok))},
                outcome="ok" if ok else "failed:drain-timeout")
            logger.info(f"fleet: scaled in replica {handle.replica_id} "
                        f"(clean={ok})")
            return handle.replica_id

    def _reconcile_dead(self) -> None:
        """Fold router-declared deaths (chaos kill, process loss) back into
        handle state. The router's takeover already requeued the victim's
        work; without this the dead replica would still look JOINED to
        scale-in and could be picked as the least-loaded drain victim."""
        dead = getattr(self.router, "dead_ids", lambda: [])()
        for rid in dead:
            h = self.handles.get(rid)
            if h is None or h.state == DEAD:
                continue
            h.kill()
            self.ledger.record(
                "replica_reap", step=self._step(), rule="fleet_reconcile",
                signal=f"router declared replica {rid} dead",
                reason=f"replica {rid} died outside the fleet's control; "
                       f"handle reconciled (work already requeued)")
            logger.warning(f"fleet: reconciled dead replica {rid}")
            # the death changed the topology: an sla_pressure rule that
            # latched in the OLD fleet (e.g. a scale-out rejected at
            # capacity) must not block the first scale-out of the new,
            # smaller one — re-arm it (cooldown and budget still apply)
            if self.supervisor is not None and \
                    getattr(self.supervisor, "guard", None) is not None:
                self.supervisor.guard.rearm("sla_pressure")

    # -- views --------------------------------------------------------------
    def _joined(self) -> List[ReplicaHandle]:
        return [h for h in self.handles.values() if h.state == JOINED]

    def _step(self) -> int:
        """Best-effort fleet step stamp for ledger entries: the max serving
        step across joined replicas (fleet time moves with its engines)."""
        steps = [getattr(h.server, "_steps", 0) for h in self._joined()
                 if h.server is not None]
        return max(steps, default=0)

    def describe(self) -> Dict[str, Any]:
        return {"replicas": {rid: h.describe()
                             for rid, h in sorted(self.handles.items())},
                "joined": [h.replica_id for h in self._joined()],
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas}

    def close(self) -> None:
        """Halt everything (tests / bench teardown; production exits drain)."""
        for h in self.handles.values():
            if h.state not in (DEAD,):
                h.kill()
        if self.router is not None:
            self.router.close()
