"""Checkpoint save/load + universal (reshardable) checkpoints.

Reference: engine ``save_checkpoint``/``load_checkpoint``
(``runtime/engine.py:3140,2794``), the pluggable ``CheckpointEngine``
(``runtime/checkpoint_engine/checkpoint_engine.py:9``), and the universal
checkpoint pipeline (``checkpoint/ds_to_universal.py``).

TPU-native design: orbax stores every array as a *logical global* tensor
regardless of how it was sharded in memory, so a checkpoint written at one
(dp, tp, pp, sp) topology restores under any other simply by passing the new
shardings — the reference's per-rank ``zero_pp_rank_*`` shard files and the
offline extract/merge reshard pipeline collapse into the storage format
itself. ``zero_to_fp32`` (offline consolidation, reference
``utils/zero_to_fp32.py``) becomes a read-with-replicated-sharding.

Layout under ``<save_dir>/<tag>/``:
  ``state/``        orbax pytree of TrainState (params, opt, loss scale, step)
  ``metadata.json`` config snapshot, topology, client_state
``<save_dir>/latest`` holds the most recent tag (reference tag file).
"""

import json
import os
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.fs import fsync_write_json, fsync_write_text
from ..utils.logging import log_dist, logger

try:
    import orbax.checkpoint as ocp
except ImportError:  # pragma: no cover
    ocp = None


class CheckpointEngine:
    """Pluggable storage backend (reference ``CheckpointEngine`` ABC)."""

    def save(self, tree: Any, path: str):
        raise NotImplementedError

    def load(self, path: str, template: Any = None, shardings: Any = None) -> Any:
        raise NotImplementedError

    def wait(self):
        pass


class OrbaxCheckpointEngine(CheckpointEngine):
    """Default engine (analogue of ``TorchCheckpointEngine``); ``use_async``
    gives background writes like the reference's Nebula/DataStates async tier."""

    def __init__(self, use_async: bool = False):
        self.use_async = use_async
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler()) if use_async \
            else ocp.Checkpointer(ocp.StandardCheckpointHandler())

    def save(self, tree: Any, path: str):
        self._ckptr.save(path, args=ocp.args.StandardSave(tree), force=True)

    def load(self, path: str, template: Any = None, shardings: Any = None,
             partial: bool = False) -> Any:
        if template is not None and shardings is not None:
            abstract = jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
                template, shardings)
            if partial:  # restore a subtree only (skips reading dropped keys)
                ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
                restore_args = ocp.checkpoint_utils.construct_restore_args(abstract)
                # transforms={} is the partial-restore spelling this orbax
                # line supports: keys absent from ``item`` are dropped
                # unread (the newer ``partial_restore=True`` kwarg does not
                # exist here)
                return ckptr.restore(path, args=ocp.args.PyTreeRestore(
                    item=abstract, restore_args=restore_args, transforms={}))
            return self._ckptr.restore(path, args=ocp.args.StandardRestore(abstract))
        return self._ckptr.restore(path)

    def wait(self):
        if self.use_async:
            self._ckptr.wait_until_finished()


# In-flight async commit threads, keyed by abspath(save_dir). The commit
# (array-write wait + metadata + 'latest') runs on a background thread; a
# reader — possibly a DIFFERENT engine pointed at the same directory, as in
# restart-recovery — must be able to rendezvous with it, so the registry is
# module-global rather than an attribute of the writing engine.
_PENDING_COMMITS: Dict[str, threading.Thread] = {}
_PENDING_LOCK = threading.Lock()


def wait_pending_commits(ckpt_dir: str) -> None:
    """Join any in-flight async checkpoint commit targeting ``ckpt_dir``."""
    with _PENDING_LOCK:
        t = _PENDING_COMMITS.get(os.path.abspath(ckpt_dir))
    if t is not None and t is not threading.current_thread() and t.is_alive():
        t.join()


def _is_committed(ckpt_dir: str, tag: str) -> bool:
    # metadata.json doubles as the commit marker: it is written atomically
    # AFTER the array write lands, so its presence certifies the tag
    return os.path.exists(os.path.join(ckpt_dir, str(tag), "metadata.json"))


def read_latest_tag(ckpt_dir: str) -> Optional[str]:
    """The newest COMMITTED tag the ``latest`` pointer names — the ONE place
    that knows the pointer format.

    A pointed tag missing its commit marker (a torn write: the process died
    between the array write and the metadata commit) is skipped in favor of
    the newest tag that did commit, so restore never dereferences a
    half-written checkpoint. No pointer at all still means None — a
    directory of ``save_latest=False`` checkpoints never designated a
    latest, and inventing one would silently load state the user did not
    ask for."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    wait_pending_commits(ckpt_dir)
    p = os.path.join(ckpt_dir, "latest")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        tag = f.read().strip()
    if not tag:
        return None
    if _is_committed(ckpt_dir, tag):
        return tag
    # torn pointer target: fall back to the newest committed tag that was
    # itself saved into the 'latest' lineage — a save_latest=False side
    # checkpoint (its metadata records that) must not be resurrected as
    # the latest just because its mtime is newest
    candidates = []
    for name in os.listdir(ckpt_dir):
        meta = os.path.join(ckpt_dir, name, "metadata.json")
        if os.path.isdir(os.path.join(ckpt_dir, name)) and os.path.exists(meta):
            try:
                with open(meta) as f:
                    in_lineage = json.load(f).get("save_latest", True)
            except (OSError, json.JSONDecodeError):
                continue  # its own commit is damaged; not a fallback target
            if in_lineage:
                candidates.append((os.path.getmtime(meta), name))
    if not candidates:
        logger.warning(
            f"checkpoint tag {tag!r} in {ckpt_dir} has no commit marker "
            "(torn write?) and no earlier committed tag exists")
        return None
    newest = max(candidates)[1]
    logger.warning(
        f"checkpoint tag {tag!r} in {ckpt_dir} has no commit marker "
        f"(torn write?) — falling back to committed tag {newest!r}")
    return newest


def _state_to_tree(engine) -> Dict[str, Any]:
    s = engine.state
    return {"step": s.step, "params": s.params, "opt_state": s.opt_state,
            "loss_scale": {"scale": s.loss_scale.scale, "good_steps": s.loss_scale.good_steps,
                           "hysteresis": s.loss_scale.hysteresis}}


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None, save_latest: bool = True):
    """Reference ``engine.save_checkpoint:3140``. Collective: every process
    must call it (orbax coordinates multi-host writes)."""
    tag = tag if tag is not None else f"global_step{engine.global_steps}"
    save_dir = os.path.abspath(save_dir)
    path = os.path.join(save_dir, str(tag))
    ck = _get_ckpt_engine(engine)
    # ordering: an async checkpointer rejects a second save() while the
    # previous one is still writing — the wait must come BEFORE this save,
    # not only inside the commit thread (which used to race this call)
    wait_pending_commits(save_dir)
    ck.wait()
    ck.save(_state_to_tree(engine), os.path.join(path, "state"))
    host_adam = getattr(engine, "_host_adam", None)
    if host_adam is not None and jax.process_index() == 0:
        # ZeRO-Offload host optimizer state (fp32 master + moments) lives
        # outside TrainState; store it beside the orbax tree
        sd = host_adam.state_dict()
        flat = {"step": np.int64(sd["step"])}
        for name in ("master", "exp_avg", "exp_avg_sq"):
            for i, leaf in enumerate(jax.tree.leaves(
                    sd[name], is_leaf=lambda x: x is None)):
                if leaf is not None:
                    flat[f"{name}_{i}"] = leaf
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "host_optimizer.npz"), **flat)
    meta = {
        "tag": str(tag),
        # recorded so the torn-pointer fallback can tell pointer-lineage
        # checkpoints from side saves the user never designated as latest
        "save_latest": bool(save_latest),
        "global_steps": engine.global_steps,
        "skipped_steps": engine.skipped_steps,
        "config": engine.config.to_dict(),
        "topology": {"pp": engine.topo.pp_size, "dp": engine.topo.dp_size,
                     "ep": engine.topo.ep_size, "sp": engine.topo.sp_size,
                     "tp": engine.topo.tp_size},
        "client_state": client_state or {},
    }
    sampler = getattr(engine, "data_sampler", None)
    if sampler is not None:
        # curriculum draw position (data_pipeline.DeepSpeedDataSampler) —
        # resume must not rewalk the difficulty schedule from step 0
        meta["data_sampler"] = sampler.state_dict()

    def _commit():
        # 'latest' must only ever point at a durable checkpoint: wait for the
        # array write to land before committing the pointer. Runs on a
        # background thread for async saves so training overlaps the write.
        # Both files go down as write-temp + fsync + atomic rename, and
        # metadata.json (the commit marker read_latest_tag checks) lands
        # BEFORE the pointer — a crash between the two leaves a valid,
        # merely unpointed, checkpoint rather than a pointed torn one.
        ck.wait()
        if jax.process_index() == 0:
            fsync_write_json(os.path.join(path, "metadata.json"), meta,
                             indent=2, default=str)
            if save_latest:
                fsync_write_text(os.path.join(save_dir, "latest"), str(tag))
        log_dist(f"saved checkpoint {path}")

    if getattr(ck, "use_async", False):
        t = threading.Thread(target=_commit, daemon=False)
        with _PENDING_LOCK:
            _PENDING_COMMITS[save_dir] = t
        t.start()
        engine._ckpt_commit_thread = t  # load_checkpoint also joins via registry
    else:
        _commit()
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True, load_lr_scheduler_states: bool = True,
                    load_module_only: bool = False):
    """Reference ``engine.load_checkpoint:2794``. Resharding to the *current*
    topology is automatic (universal-checkpoint semantics, reference
    ``load_universal_checkpoint`` flag ``engine.py:867``): the stored global
    arrays are re-laid-out onto this engine's shardings."""
    load_dir = os.path.abspath(load_dir)
    # an in-flight async save must land before we read 'latest' — including
    # one started by a DIFFERENT engine in this process (the registry), and
    # this engine's own writes to other directories (the attribute)
    wait_pending_commits(load_dir)
    pending = getattr(engine, "_ckpt_commit_thread", None)
    if pending is not None and pending.is_alive():
        pending.join()
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            logger.warning(f"no 'latest' file in {load_dir}; nothing loaded")
            return None, {}
    path = os.path.join(load_dir, str(tag))
    ck = _get_ckpt_engine(engine)

    params_only = load_module_only or not load_optimizer_states
    template = _state_to_tree(engine)
    if params_only:  # don't read + reshard ~2x-params of optimizer state just to drop it
        template = {"params": template["params"]}
    shardings = jax.tree.map(lambda x: x.sharding, template)
    tree = ck.load(os.path.join(path, "state"), template=template, shardings=shardings,
                   partial=params_only)

    from ..runtime.engine import TrainState
    from ..runtime.loss_scaler import LossScaleState

    if params_only:
        opt_state = engine.state.opt_state
        step = engine.state.step
        ls = engine.state.loss_scale
    else:
        opt_state, step = tree["opt_state"], tree["step"]
        ls = LossScaleState(scale=tree["loss_scale"]["scale"],
                            good_steps=tree["loss_scale"]["good_steps"],
                            hysteresis=tree["loss_scale"]["hysteresis"])
    # loading a checkpoint jumps to different params: a stale error-feedback
    # residual must not replay into them — keep the structure (compiled
    # steps expect it) but zero the carry
    engine.state = TrainState(step=step, params=tree["params"], opt_state=opt_state,
                              loss_scale=ls,
                              comm_feedback=jax.tree.map(
                                  jax.numpy.zeros_like,
                                  engine.state.comm_feedback))

    host_adam = getattr(engine, "_host_adam", None)
    if host_adam is not None:
        host_npz = os.path.join(path, "host_optimizer.npz")
        if not params_only and os.path.exists(host_npz):
            data = np.load(host_npz)
            sd = {"step": int(data["step"])}
            for name in ("master", "exp_avg", "exp_avg_sq"):
                ref = getattr(host_adam, name)
                flat = jax.tree.leaves(ref, is_leaf=lambda x: x is None)
                restored = [data[f"{name}_{i}"] if f"{name}_{i}" in data else None
                            for i in range(len(flat))]
                treedef = jax.tree.structure(ref, is_leaf=lambda x: x is None)
                sd[name] = jax.tree.unflatten(treedef, restored)
            host_adam.load_state_dict(sd)
        else:
            # no host state in this checkpoint (params-only load, or saved
            # without offload): re-seed the masters from the loaded params so
            # the next step doesn't overwrite them with stale init-time ones
            logger.warning("host optimizer state not restored — re-seeding "
                           "fp32 masters from the loaded params")
            host_adam.reseed_masters(jax.device_get(tree["params"]))

    meta_path = os.path.join(path, "metadata.json")
    meta = json.load(open(meta_path)) if os.path.exists(meta_path) else {}
    engine.global_steps = meta.get("global_steps", int(np.asarray(step)))
    engine.skipped_steps = meta.get("skipped_steps", 0)
    sampler = getattr(engine, "data_sampler", None)
    if sampler is not None:
        if meta.get("data_sampler"):
            sampler.load_state_dict(meta["data_sampler"])
        else:
            logger.warning(
                "checkpoint has no data_sampler state (written before the "
                "curriculum sampler existed, or without one) — the "
                "curriculum will rewalk its schedule from step 0")
    log_dist(f"loaded checkpoint {path} (saved at topology {meta.get('topology')})")
    return path, meta.get("client_state", {})


def _get_ckpt_engine(engine) -> CheckpointEngine:
    if getattr(engine, "_ckpt_engine", None) is None:
        engine._ckpt_engine = OrbaxCheckpointEngine(
            use_async=engine.config.checkpoint.async_save)
    return engine._ckpt_engine


# ---------------------------------------------------------------------------
# Offline tools
# ---------------------------------------------------------------------------


def zero_to_fp32(checkpoint_dir: str, output_file: Optional[str] = None, tag: Optional[str] = None):
    """Consolidate a checkpoint into a flat fp32 numpy ``.npz`` of params
    (reference ``utils/zero_to_fp32.py`` — there it must merge ZeRO shard
    files; here the store is already logical-global, so this is a read)."""
    checkpoint_dir = os.path.abspath(checkpoint_dir)
    if tag is None:
        tag = read_latest_tag(checkpoint_dir)
        if tag is None:
            raise FileNotFoundError(f"no 'latest' file in {checkpoint_dir}")
    path = os.path.join(checkpoint_dir, str(tag), "state")
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    tree = ckptr.restore(path)
    params = tree["params"]
    flat = {"/".join(map(str, [getattr(e, 'key', e) for e in kp])): np.asarray(v, np.float32)
            for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    if output_file:
        np.savez(output_file, **flat)
        logger.info(f"wrote {len(flat)} fp32 tensors to {output_file}")
    return flat


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str, tag: Optional[str] = None):
    """Reference ``get_fp32_state_dict_from_zero_checkpoint`` API."""
    return zero_to_fp32(checkpoint_dir, output_file=None, tag=tag)
