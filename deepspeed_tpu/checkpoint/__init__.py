from .deepspeed_checkpoint import DeepSpeedCheckpoint
from .state_dict_factory import (SDLoader, SDLoaderFactory, merge_qkv,
                                 merge_state_dicts, split_qkv,
                                 split_state_dict)

__all__ = ["DeepSpeedCheckpoint", "SDLoaderFactory", "SDLoader",
           "merge_state_dicts", "split_state_dict", "merge_qkv", "split_qkv"]
