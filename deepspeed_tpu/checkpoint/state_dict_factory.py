"""TP-aware merge/split of checkpoint state dicts.

Reference: ``deepspeed/runtime/state_dict_factory.py`` — ``SDLoaderFactory`` /
``MegatronSDLoader`` re-partition Megatron-style checkpoint shards when the
serving TP degree differs from the saved one (``merge_state_dict:301``,
``split_state_dict:350``), with special handling for fused query-key-value
weights whose head layout differs by checkpoint version
(``merge_query_key_value:220``, ``split_query_key_value:258``).

TPU-native redesign: a state dict here is a flat/nested pytree of numpy
arrays, and the TP layout is *described by PartitionSpecs* (from an explicit
tree or AutoTP's ``tp_parser``) instead of being hard-coded per layer class.
Merging N shards = concatenating each leaf along its sharded dim; splitting =
host-side slicing (never materializing on device), so a 70B checkpoint
re-partitions with O(one leaf) peak memory above the shard files.

Fused-QKV layouts (the reference's version switch,
``split_query_key_value:277``) are expressed as an explicit ``qkv_layout``
per leaf: ``"concat"`` ([q | k | v] blocks — Megatron ckpt_ver 0, each third
sliced separately) or ``"interleaved"`` (whole-head-contiguous groups —
Megatron ckpt_ver 1.0/2.0, bloom/neox; a plain contiguous slice keeps whole
heads).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from ..module_inject.auto_tp import (flatten_with_paths,
                                     shard_checkpoint_leaf, sharded_dim,
                                     tp_parser)
from ..utils.logging import log_dist

__all__ = ["SDLoaderFactory", "merge_state_dicts", "split_state_dict",
           "merge_qkv", "split_qkv", "megatron_specs", "save_shard_npz"]

# reserved npz key: sidecar list of leaf paths a split pass replicated
# (merge reads it back so constant-content shards round-trip exactly)
_REPLICATED_KEY = "__replicated_paths__"


# ---------------------------------------------------------------------------
# Megatron torch-layout spec table (ADVICE r3: auto_tp name heuristics assume
# the flax [in, out] kernel layout; Megatron torch weights are [out, in], so
# col-parallel shards dim 0 and row-parallel shards dim 1 — inferring them
# with tp_parser silently merges along the wrong axis)
# ---------------------------------------------------------------------------

_MEG_COL = ("query_key_value", "dense_h_to_4h", "query", "key_value", "qkv")
_MEG_ROW = ("attention/dense", "self_attention/dense", "dense_4h_to_h")
_MEG_VOCAB = ("word_embeddings", "lm_head", "embed_out", "final_linear")
_MEG_REPLICATED = ("position_embeddings", "layernorm", "norm", "bias_gelu")


def _meg_match(name: str, pats) -> bool:
    # boundary-aware matching ('/' in a pattern hits '.' too) — shared with
    # AutoTP so the two name vocabularies can't drift
    from ..module_inject.auto_tp import _matches

    return _matches(pats, name.lower())


def megatron_specs(tree: Any, axis: str = "tp", *, strict: bool = True) -> Any:
    """Explicit PartitionSpec tree for Megatron-GPT-style checkpoints in the
    torch ``[out, in]`` layout (reference ``MegatronSDLoader`` hard-codes the
    same per-layer knowledge, ``state_dict_factory.py:380``).

    col-parallel -> dim 0, row-parallel -> dim 1, word embeddings -> dim 0,
    norms/position embeddings/1-D row biases -> replicated. ``strict=True``
    raises on an unmatched 2-D leaf instead of silently replicating (the
    silent path is how a multi-shard merge corrupts weights)."""
    paths, leaves, treedef = flatten_with_paths(tree)
    specs = []
    for path, leaf in zip(paths, leaves):
        nd = getattr(leaf, "ndim", np.asarray(leaf).ndim)
        low = path.lower()
        if _meg_match(low, _MEG_REPLICATED):
            specs.append(P())  # spec-ok: megatron import table: names the layout being read, not chosen
        elif _meg_match(low, _MEG_ROW):
            # row-parallel: weight shards the input dim (1 in [out, in]);
            # its bias is a full output vector -> replicated
            specs.append(P(None, axis) if nd == 2 else P())  # spec-ok: megatron import table row-parallel entry
        elif _meg_match(low, _MEG_COL):
            # col-parallel: weight shards the output dim (0); bias too
            specs.append(P(axis) if nd >= 1 else P())  # spec-ok: megatron import table col-parallel entry
        elif _meg_match(low, _MEG_VOCAB):
            # vocab-parallel shards dim 0 for the embedding matrix AND for a
            # 1-D output-layer bias (Megatron shards lm_head.bias along vocab
            # too — replicating it here would merge it by the wrong rule)
            specs.append(P(axis) if nd >= 1 else P())  # spec-ok: megatron import table vocab-parallel entry
        elif nd >= 2:
            if strict:
                raise ValueError(
                    f"megatron_specs: unmatched 2-D leaf {path!r} — add it to "
                    "the layout table or pass strict=False (replicates it)")
            specs.append(P())  # spec-ok: megatron import fallback: replicate unmatched leaves
        else:
            specs.append(P())  # spec-ok: megatron import fallback: replicate 1-D leaves
    return jax.tree_util.tree_unflatten(treedef, specs)


def save_shard_npz(path: str, tree: Any,
                   replicated_paths: Optional[Iterable[str]] = None) -> None:
    """Write one TP shard as a flat ``.npz`` ('/'-joined keys), persisting
    the replicated-leaf sidecar so a later merge doesn't need the content
    heuristic (ADVICE r3: the factory merge path couldn't see
    ``replicated_paths``)."""
    paths, leaves, _ = flatten_with_paths(tree)
    flat = {p: np.asarray(l) for p, l in zip(paths, leaves)}
    if replicated_paths is not None:
        # always write the key (an EMPTY set is authoritative too: it tells
        # the merge that every identical-content leaf is a true shard)
        # let numpy size the string dtype — a fixed width would silently
        # truncate long leaf paths and break their recognition on merge
        flat[_REPLICATED_KEY] = np.asarray(sorted(replicated_paths))
    np.savez(path, **flat)


# ---------------------------------------------------------------------------
# Fused-QKV layout math (reference merge/split_query_key_value)
# ---------------------------------------------------------------------------


def split_qkv(value: np.ndarray, rank: int, size: int, *, num_heads: int,
              layout: str = "concat", dim: int = -1) -> np.ndarray:
    """Slice one fused-QKV weight so each rank gets whole heads of q, k, v.

    ``concat``: the fused dim is [q_heads | k_heads | v_heads] — each third
    is sliced independently and re-concatenated (reference ckpt_ver==0 path,
    ``split_query_key_value:279``).
    ``interleaved``: the fused dim is [h0:(q,k,v), h1:(q,k,v), ...] — a plain
    contiguous slice keeps whole (q,k,v) head groups together (reference
    ckpt_ver 1.0/2.0 path, ``:292``).
    """
    dim = dim % value.ndim
    n = value.shape[dim]
    if n % (3 * num_heads):
        raise ValueError(f"fused qkv dim {n} not divisible by 3*{num_heads}")
    if num_heads % size:
        raise ValueError(f"num_heads={num_heads} not divisible by tp={size}")
    if layout == "interleaved":
        step = n // size
        idx = [slice(None)] * value.ndim
        idx[dim] = slice(rank * step, (rank + 1) * step)
        return np.ascontiguousarray(value[tuple(idx)])
    if layout != "concat":
        raise ValueError(f"unknown qkv layout {layout!r}")
    third = n // 3
    step = third // size
    parts = []
    for t in range(3):
        idx = [slice(None)] * value.ndim
        idx[dim] = slice(t * third + rank * step, t * third + (rank + 1) * step)
        parts.append(value[tuple(idx)])
    return np.ascontiguousarray(np.concatenate(parts, axis=dim))


def merge_qkv(values: Sequence[np.ndarray], *, layout: str = "concat",
              dim: int = -1) -> np.ndarray:
    """Inverse of :func:`split_qkv` (reference ``merge_query_key_value:220``)."""
    dim = dim % values[0].ndim
    if layout == "interleaved":
        return np.concatenate(values, axis=dim)
    if layout != "concat":
        raise ValueError(f"unknown qkv layout {layout!r}")
    thirds: List[List[np.ndarray]] = [[], [], []]
    for v in values:
        n = v.shape[dim]
        if n % 3:
            raise ValueError(f"fused qkv shard dim {n} not divisible by 3")
        step = n // 3
        for t in range(3):
            idx = [slice(None)] * v.ndim
            idx[dim] = slice(t * step, (t + 1) * step)
            thirds[t].append(v[tuple(idx)])
    return np.ascontiguousarray(np.concatenate(
        [np.concatenate(t, axis=dim) for t in thirds], axis=dim))


# ---------------------------------------------------------------------------
# Whole-tree merge / split
# ---------------------------------------------------------------------------


def merge_state_dicts(shards: Sequence[Any], specs: Any = None, *,
                      axis: str = "tp",
                      qkv_leaves: Optional[Dict[str, str]] = None,
                      split_size: Optional[int] = None,
                      replicated_paths: Optional[Iterable[str]] = None) -> Any:
    """Merge TP shard pytrees into one full pytree.

    ``specs``: PartitionSpec tree (default: AutoTP name inference on the
    first shard — sharded dims are found by *comparing shapes is not
    possible* for already-sliced shards, so the spec tree is authoritative).
    ``qkv_leaves``: path → layout for fused-QKV leaves needing the
    version-aware merge. ``split_size``: the TP degree the shards were
    *written* at (defaults to ``len(shards)``).

    ``replicated_paths`` (authoritative when given; get it from
    ``split_state_dict(..., return_replicated=True)``): which leaves the
    split pass replicated. Without it a heuristic applies — identical shards
    whose dim is indivisible by ``split_size`` are treated as replicas. The
    heuristic is provably ambiguous for *constant-content* leaves: (a) a
    sharded leaf whose shard dim is indivisible by the degree (e.g. a zero
    GQA bias [2, dh] split 2-ways to [1, dh]), and (b) a zero-init 1-D
    vocab-parallel bias (identical V/n shards look like an old-format
    replicated full bias, and merge to the shard shape). Thread
    ``replicated_paths`` when exact round-trips of constant leaves matter.
    """
    if not shards:
        raise ValueError("no shards to merge")
    if specs is None:
        specs = tp_parser(shards[0], axis=axis)
    qkv_leaves = qkv_leaves or {}
    repl = None if replicated_paths is None else frozenset(replicated_paths)

    paths, leaves0, treedef = flatten_with_paths(shards[0])
    rest = [flatten_with_paths(s)[1] for s in shards[1:]]
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    out = []
    for i, (path, leaf0, spec) in enumerate(zip(paths, leaves0, spec_leaves)):
        vals = [np.asarray(leaf0)] + [np.asarray(r[i]) for r in rest]
        dim = sharded_dim(spec, axis)
        if repl is not None:
            if path in repl:
                dim = None
        elif dim is not None:
            # Heuristic replica detection (see docstring for the ambiguous
            # corners): identical shards + indivisible dim => replica. A
            # cleanly divisible dim is treated as a real shard — EXCEPT 1-D
            # vocab leaves, where identical content means an old-format
            # shard set that replicated the full bias (files written before
            # 1-D vocab leaves were sharded carry no sidecar). Trained vocab
            # biases are never bit-identical across true shards; a
            # zero-init sharded vocab bias is the documented ambiguity —
            # thread ``replicated_paths`` for exactness. Content comparison
            # is evaluated lazily so divisible 2-D weights keep the cheap
            # modulo-only path (O(one-leaf) merge traffic).
            n_split = split_size or len(vals)
            def _identical():
                return all(v.shape == vals[0].shape
                           and np.array_equal(v, vals[0])
                           for v in vals[1:])
            if vals[0].shape[dim] % n_split != 0:
                if _identical():
                    dim = None
            elif vals[0].ndim == 1 and _meg_match(path.lower(), _MEG_VOCAB):
                if _identical():
                    dim = None
        if path in qkv_leaves and dim is not None:
            out.append(merge_qkv(vals, layout=qkv_leaves[path], dim=dim))
            continue
        if dim is None:
            out.append(vals[0])
        else:
            out.append(np.ascontiguousarray(np.concatenate(vals, axis=dim)))
    return jax.tree_util.tree_unflatten(treedef, out)


def split_state_dict(sd: Any, rank: int, size: int, specs: Any = None, *,
                     axis: str = "tp",
                     qkv_leaves: Optional[Dict[str, str]] = None,
                     num_heads: Optional[int] = None,
                     return_replicated: bool = False) -> Any:
    """Slice a full pytree to one TP rank's shard (host-side numpy).

    ``return_replicated=True`` additionally returns the frozenset of leaf
    paths that stayed replicated (spec said replicate, or an indivisible
    dim) — feed it to ``merge_state_dicts(replicated_paths=...)`` for exact
    round-trips.
    """
    if specs is None:
        specs = tp_parser(sd, axis=axis, tp_size=size)
    qkv_leaves = qkv_leaves or {}

    paths, leaves, treedef = flatten_with_paths(sd)
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    out = []
    replicated = set()
    for path, leaf, spec in zip(paths, leaves, spec_leaves):
        val = np.asarray(leaf)
        if path in qkv_leaves:
            if num_heads is None:
                raise ValueError("qkv_leaves given but num_heads is None")
            dim = sharded_dim(spec, axis)
            out.append(split_qkv(val, rank, size, num_heads=num_heads,
                                 layout=qkv_leaves[path],
                                 dim=dim if dim is not None else -1))
        else:
            shard = shard_checkpoint_leaf(val, spec, axis, rank, size)
            if shard.shape == val.shape and size > 1:
                replicated.add(path)
            out.append(shard)
    tree = jax.tree_util.tree_unflatten(treedef, out)
    if return_replicated:
        return tree, frozenset(replicated)
    return tree


class SDLoaderFactory:
    """Reference ``SDLoaderFactory`` vocabulary: pick a loader and produce the
    state dict for (mp_world_size, mp_rank) from a list of saved shards.

    ``ckpt_list`` entries are either in-memory pytrees or paths to ``.npz``
    files (flat key → array, '/'-joined paths) — the TPU-native serialized
    shard format (orbax handles the full logical-global checkpoints;
    this factory serves the reference's raw-shard re-partition flow).
    """

    @staticmethod
    def get_sd_loader(ckpt_list: Sequence[Any], sd_type: str = "Megatron",
                      version: Optional[int] = None, **kwargs) -> "SDLoader":
        """``kwargs`` pass through to :class:`SDLoader` (``specs``,
        ``qkv_leaves``, ``num_heads`` — the split path *requires* num_heads
        when the checkpoint has fused-QKV leaves)."""
        if sd_type.lower() not in ("megatron", "auto"):
            raise ValueError(f"unsupported sd_type {sd_type!r}")
        return SDLoader(list(ckpt_list), version=version, **kwargs)


class SDLoader:
    def __init__(self, ckpt_list: Sequence[Any], version: Optional[int] = None,
                 specs: Any = None, qkv_leaves: Optional[Dict[str, str]] = None,
                 num_heads: Optional[int] = None, layout: str = "flax",
                 replicated_paths: Optional[Iterable[str]] = None):
        """``layout='megatron'``: build specs with the explicit torch
        ``[out, in]`` table (:func:`megatron_specs`) instead of AutoTP's flax
        name heuristics — required for real Megatron shards (ADVICE r3: the
        flax assumption merged QKV along the wrong axis and replicated
        row-parallel dense weights). ``replicated_paths`` (or an in-file
        sidecar written by :func:`save_shard_npz`) makes merges exact for
        constant-content leaves."""
        self.ckpt_list = list(ckpt_list)
        self.version = version
        self.layout = layout
        self.specs = specs
        self._explicit_replicated = (None if replicated_paths is None
                                     else frozenset(replicated_paths))
        self._sidecar_replicated: set = set()
        self._sidecar_seen = False
        # reference merge/split_query_key_value (state_dict_factory.py:220):
        # version 0 stores [q | k | v] BLOCKS (split per third across TP);
        # versions 1.0/2.0 store whole-head-contiguous layouts that TP-split
        # as a plain slice (our "interleaved" handling). Unknown version
        # defaults to the modern plain-slice layout.
        default_layout = ("concat" if (version is not None and version == 0)
                          else "interleaved")
        self.qkv_layout = default_layout
        self.qkv_leaves = qkv_leaves
        self.num_heads = num_heads

    def _load_one(self, entry) -> Any:
        if isinstance(entry, str):
            with np.load(entry) as z:
                flat = {k: z[k] for k in z.files}
            sidecar = flat.pop(_REPLICATED_KEY, None)
            if sidecar is not None:
                self._sidecar_seen = True
                self._sidecar_replicated.update(str(p) for p in sidecar)
            tree: Dict[str, Any] = {}
            for k, v in flat.items():
                node = tree
                parts = k.split("/")
                for p in parts[:-1]:
                    node = node.setdefault(p, {})
                node[parts[-1]] = v
            return tree
        return entry

    def _specs_for(self, tree) -> Any:
        if self.specs is not None:
            return self.specs
        if self.layout == "megatron":
            return megatron_specs(tree)
        return None  # merge/split fall back to tp_parser (flax layout)

    def _replicated(self) -> Optional[frozenset]:
        if self._explicit_replicated is not None:
            return self._explicit_replicated
        return frozenset(self._sidecar_replicated) if self._sidecar_seen else None

    def _auto_qkv(self, tree) -> Dict[str, str]:
        if self.qkv_leaves is not None:
            return self.qkv_leaves
        found = {}
        for path in flatten_with_paths(tree)[0]:
            low = path.lower()
            if any(t in low for t in ("query_key_value", "qkv", "c_attn")):
                found[path] = self.qkv_layout
        return found

    def load(self, mp_world_size: int, mp_rank: int) -> Any:
        """Reference ``SDLoaderBase.load:57``: produce this rank's state dict,
        merging or splitting as the saved/serving TP degrees require."""
        n = len(self.ckpt_list)
        if mp_world_size == n:
            return self._load_one(self.ckpt_list[mp_rank])
        if mp_world_size < n:  # merge: this rank owns n//mp ckpt shards
            if n % mp_world_size:
                raise ValueError(f"cannot merge {n} shards to tp={mp_world_size}")
            per = n // mp_world_size
            shards = [self._load_one(c)
                      for c in self.ckpt_list[mp_rank * per:(mp_rank + 1) * per]]
            log_dist(f"sd_factory: merging {per} shards for mp_rank {mp_rank}")
            return merge_state_dicts(shards, self._specs_for(shards[0]),
                                     qkv_leaves=self._auto_qkv(shards[0]),
                                     split_size=n,
                                     replicated_paths=self._replicated())
        # split: this rank slices one saved shard
        if mp_world_size % n:
            raise ValueError(f"cannot split {n} shards to tp={mp_world_size}")
        per = mp_world_size // n
        src = self._load_one(self.ckpt_list[mp_rank // per])
        log_dist(f"sd_factory: splitting shard {mp_rank // per} "
                 f"{per}-way for mp_rank {mp_rank}")
        return split_state_dict(src, mp_rank % per, per, self._specs_for(src),
                                qkv_leaves=self._auto_qkv(src),
                                num_heads=self.num_heads)
