"""Checkpoint inspector — the reference ``DeepSpeedCheckpoint`` vocabulary.

Reference ``deepspeed/checkpoint/deepspeed_checkpoint.py:37`` walks a raw
mp_rank/layer shard directory to answer the topology/content questions the
universal-checkpoint tooling asks (source tp/pp/dp degrees, layer keys,
state access). Our checkpoints are orbax logical-global trees — reshardable
by construction — so this class is a *reader* over
``<dir>/<tag>/{state, metadata.json}`` exposing the same questions.
"""

import json
import os
from typing import Any, Dict, List, Optional

__all__ = ["DeepSpeedCheckpoint"]


class DeepSpeedCheckpoint:
    def __init__(self, dir: str, tag: Optional[str] = None):
        from .engine import read_latest_tag

        self.dir = os.path.abspath(dir)
        if tag is None:
            tag = read_latest_tag(self.dir)
            if tag is None:
                raise FileNotFoundError(
                    f"no 'latest' tag file in {self.dir}; pass tag= explicitly")
        self.tag = str(tag)
        self.path = os.path.join(self.dir, self.tag)
        meta_path = os.path.join(self.path, "metadata.json")
        if not os.path.exists(meta_path):
            raise FileNotFoundError(f"not a deepspeed_tpu checkpoint: {meta_path}")
        with open(meta_path) as f:
            self.metadata: Dict[str, Any] = json.load(f)
        topo = self.metadata.get("topology", {})
        self.tp_degree = int(topo.get("tp", 1))
        self.pp_degree = int(topo.get("pp", 1))
        self.dp_degree = int(topo.get("dp", 1))
        self.ep_degree = int(topo.get("ep", 1))
        self.sp_degree = int(topo.get("sp", 1))
        # dp already folds ep in the 5-axis topology (dp = dp_outer * ep)
        self.original_world_size = (self.tp_degree * self.pp_degree
                                    * self.dp_degree * self.sp_degree)
        self.world_size = self.original_world_size
        self.global_steps = int(self.metadata.get("global_steps", 0))
        self.client_state = self.metadata.get("client_state", {})
        self._tree = None  # load_state_tree cache (reads are expensive)

    # -- discovery ------------------------------------------------------
    @staticmethod
    def get_tags(dir: str) -> List[str]:
        """All checkpoint tags under ``dir``, in chronological order for
        auto-generated tags (natural sort: global_step10 > global_step9)."""
        import re

        def natural(name):
            return [int(t) if t.isdigit() else t
                    for t in re.split(r"(\d+)", name)]

        return sorted((name for name in os.listdir(os.path.abspath(dir))
                       if os.path.exists(os.path.join(dir, name, "metadata.json"))),
                      key=natural)

    def validate_files(self) -> None:
        """Reference ``validate_files``: the state tree must exist."""
        state = os.path.join(self.path, "state")
        if not os.path.isdir(state):
            raise FileNotFoundError(f"checkpoint state missing: {state}")

    # -- content --------------------------------------------------------
    def load_state_tree(self) -> Any:
        """The full saved tree (params/opt_state/step/...) as host arrays —
        no template needed, orbax restores the stored structure. Cached:
        repeat inspections must not re-read the (multi-GB) store."""
        if self._tree is None:
            from .engine import OrbaxCheckpointEngine

            self._tree = OrbaxCheckpointEngine().load(
                os.path.join(self.path, "state"))
        return self._tree

    def get_layer_keys(self) -> List[str]:
        """Top-level parameter group names (reference layer_keys — there,
        layer-file prefixes; here, the param tree's first level)."""
        tree = self.load_state_tree()
        params = tree.get("params", tree) if isinstance(tree, dict) else tree
        return sorted(params) if isinstance(params, dict) else []

    def show_3d_mapping(self) -> Dict[str, int]:
        """Reference debug helper: the source parallel degrees."""
        return {"tp": self.tp_degree, "pp": self.pp_degree,
                "dp": self.dp_degree, "ep": self.ep_degree,
                "sp": self.sp_degree}
