"""MoE gating + dispatch math (GShard-style).

Reference: ``deepspeed/moe/sharded_moe.py`` — ``top1gating:183``,
``top2gating:290``, ``topkgating:374``, ``MOELayer:533`` with einsum dispatch
around all-to-alls. The gating math is pure tensor algebra and carries over;
the *dispatch* is TPU-native: instead of explicit ``_AllToAll`` autograd ops,
expert-major tensors get sharding constraints (groups over dp, experts over
the ``ep`` mesh axis) and XLA lowers the resharding to ICI all-to-alls.

Shapes follow GShard: tokens [G, S, D] (G groups = batch), gates [G, S, E],
dispatch/combine [G, S, E, C] with static capacity C.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def compute_capacity(k: int, tokens_per_group: int, num_experts: int,
                     capacity_factor: float, min_capacity: int = 4) -> int:
    cap = int(np.ceil(k * tokens_per_group * capacity_factor / num_experts))
    return max(cap, min_capacity)


def load_balance_aux(gates: jnp.ndarray) -> jnp.ndarray:
    """GShard load-balance loss from the top-1 assignment (reference
    ``top1gating:183``): E * mean_e(mean-prob_e * assigned-fraction_e)."""
    g, s, e = gates.shape
    top1 = jnp.argmax(gates, axis=-1)
    me = jnp.mean(gates, axis=1)                            # [G,E] mean prob
    ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=1)
    return jnp.mean(jnp.sum(me * ce, axis=-1)) * e


def topk_gating(logits: jnp.ndarray, k: int, capacity: int,
                rng: Optional[jax.Array] = None,
                noisy_gate_policy: Optional[str] = None,
                drop_tokens: bool = True,
                norm_topk: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Generic top-k gating with capacity (covers reference top1/top2/topk).

    Returns (dispatch [G,S,E,C] bool, combine [G,S,E,C] f32, aux_loss scalar).
    """
    g, s, e = logits.shape
    logits = logits.astype(jnp.float32)
    if noisy_gate_policy == "RSample" and rng is not None:
        logits = logits + jax.random.normal(rng, logits.shape) / e
    gates = jax.nn.softmax(logits, axis=-1)  # [G,S,E]
    aux_loss = load_balance_aux(gates)

    remaining = gates
    committed = jnp.zeros((g, 1, e), jnp.float32)  # tokens assigned per expert so far
    dispatch = jnp.zeros((g, s, e, capacity), jnp.bool_)
    combine = jnp.zeros((g, s, e, capacity), jnp.float32)
    denom = jnp.zeros((g, s), jnp.float32)

    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                # [G,S]
        mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)    # [G,S,E]
        gate_k = jnp.sum(gates * mask, axis=-1)             # [G,S]
        # capacity slot = tokens assigned to this expert earlier in this round
        # + total committed in previous rounds (reference top2gating locations2
        # offset by sum(mask1))
        pos_in_expert = jnp.cumsum(mask, axis=1) - mask + committed  # [G,S,E]
        pos = jnp.sum(pos_in_expert * mask, axis=-1)        # [G,S]
        keep = pos < capacity if drop_tokens else jnp.ones_like(pos, jnp.bool_)
        pos_c = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        slot = mask[..., None] * pos_c[:, :, None, :] * keep[:, :, None, None]  # [G,S,E,C]
        dispatch = dispatch | (slot > 0)
        combine = combine + slot * gate_k[:, :, None, None]
        denom = denom + gate_k * keep
        committed = committed + jnp.sum(mask, axis=1, keepdims=True)
        remaining = remaining * (1.0 - mask)

    if norm_topk:
        # renormalize combine weights over the k selected experts (reference
        # top2gating denominator; qwen2_moe norm_topk_prob=False skips this)
        combine = combine / jnp.maximum(denom, 1e-9)[:, :, None, None]
    return dispatch, combine, aux_loss


def moe_dispatch(x: jnp.ndarray, dispatch: jnp.ndarray) -> jnp.ndarray:
    """tokens [G,S,D] x dispatch [G,S,E,C] -> expert inputs [E, G, C, D].
    Expert-major layout so the 'ep' sharding sits on dim 0."""
    return jnp.einsum("gsec,gsd->egcd", dispatch.astype(x.dtype), x)


def moe_combine(expert_out: jnp.ndarray, combine: jnp.ndarray) -> jnp.ndarray:
    """expert outputs [E,G,C,D] x combine [G,S,E,C] -> tokens [G,S,D]."""
    return jnp.einsum("egcd,gsec->gsd", expert_out, combine.astype(expert_out.dtype))


def dropless_moe(x: jnp.ndarray, gates: jnp.ndarray, k: int,
                 w_gate: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
                 activation: str = "swiglu",
                 norm_topk: bool = True,
                 b_up: jnp.ndarray = None, b_down: jnp.ndarray = None,
                 b_gate: jnp.ndarray = None) -> jnp.ndarray:
    """Dropless MoE via grouped GEMM (``jax.lax.ragged_dot``).

    TPU-native replacement for the reference CUTLASS grouped ``moe_gemm``
    (``inference/v2/kernels/cutlass_ops/moe_gemm/``) and the megablocks-style
    dropless path: every token reaches its top-k experts (no capacity, no
    zero-padded compute). Tokens are sorted by expert id; ``ragged_dot``
    multiplies each contiguous group against its expert's weights on the MXU
    without materializing per-expert padding.

    x: [G, S, D]; gates: [G, S, E] fp32 router probabilities;
    w_gate/w_up: [E, D, F]; w_down: [E, F, D]. Returns [G, S, D].
    """
    g, s, d = x.shape
    e = gates.shape[-1]
    n = g * s
    xf = x.reshape(n, d)
    gf = gates.reshape(n, e)

    top_w, top_e = jax.lax.top_k(gf, k)                     # [N, k]
    if norm_topk:
        top_w = top_w / jnp.maximum(jnp.sum(top_w, axis=-1, keepdims=True), 1e-9)
    eid = top_e.reshape(-1)                                 # [N*k]
    wts = top_w.reshape(-1)                                 # [N*k]
    order = jnp.argsort(eid)                                # expert-sorted copies
    tok_of = order // k                                     # source token per copy
    xs = xf[tok_of]                                         # [N*k, D]
    group_sizes = jnp.bincount(eid, length=e).astype(jnp.int32)

    wu = w_up.astype(x.dtype)
    wd = w_down.astype(x.dtype)
    eid_sorted = eid[order]                                 # expert per row
    up = jax.lax.ragged_dot(xs, wu, group_sizes)
    if b_up is not None:  # megatron-MoE experts carry biases
        up = up + b_up.astype(x.dtype)[eid_sorted]
    if activation == "swiglu":
        wg = w_gate.astype(x.dtype)
        gt = jax.lax.ragged_dot(xs, wg, group_sizes)
        if b_gate is not None:
            gt = gt + b_gate.astype(x.dtype)[eid_sorted]
        h = jax.nn.silu(gt) * up
    else:  # w_gate is None for ungated activations
        h = jax.nn.gelu(up)
    out = jax.lax.ragged_dot(h, wd, group_sizes)            # [N*k, D]
    if b_down is not None:
        out = out + b_down.astype(x.dtype)[eid_sorted]

    out = out * wts[order][:, None].astype(out.dtype)
    yf = jnp.zeros((n, d), out.dtype).at[tok_of].add(out)
    return yf.reshape(g, s, d)
