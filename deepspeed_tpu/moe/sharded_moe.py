"""MoE gating + dispatch math (GShard-style).

Reference: ``deepspeed/moe/sharded_moe.py`` — ``top1gating:183``,
``top2gating:290``, ``topkgating:374``, ``MOELayer:533`` with einsum dispatch
around all-to-alls. The gating math is pure tensor algebra and carries over;
the *dispatch* is TPU-native: instead of explicit ``_AllToAll`` autograd ops,
expert-major tensors get sharding constraints (groups over dp, experts over
the ``ep`` mesh axis) and XLA lowers the resharding to ICI all-to-alls.

Shapes follow GShard: tokens [G, S, D] (G groups = batch), gates [G, S, E],
dispatch/combine [G, S, E, C] with static capacity C.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def compute_capacity(k: int, tokens_per_group: int, num_experts: int,
                     capacity_factor: float, min_capacity: int = 4) -> int:
    cap = int(np.ceil(k * tokens_per_group * capacity_factor / num_experts))
    return max(cap, min_capacity)


def load_balance_aux(gates: jnp.ndarray,
                     used_token: Optional[jnp.ndarray] = None,
                     sel_gates: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """GShard load-balance loss from the top-1 assignment (reference
    ``top1gating:183``): E * mean_e(mean-prob_e * assigned-fraction_e).
    ``used_token [G,S]`` excludes padding tokens from the assigned-fraction
    term (reference ``sharded_moe.py:207`` masks ``mask1`` before ``ce``).
    ``sel_gates`` supplies the (possibly noised) scores that drove expert
    selection — the assigned-fraction mask follows the actual assignment
    while ``me`` stays on clean probabilities (reference RSample path)."""
    g, s, e = gates.shape
    top1 = jnp.argmax(gates if sel_gates is None else sel_gates, axis=-1)
    me = jnp.mean(gates, axis=1)                            # [G,E] mean prob
    hot = jax.nn.one_hot(top1, e, dtype=jnp.float32)
    if used_token is not None:
        hot = hot * used_token.astype(jnp.float32)[..., None]
    ce = jnp.mean(hot, axis=1)
    return jnp.mean(jnp.sum(me * ce, axis=-1)) * e


def _rts_rank(mask: jnp.ndarray, rng: jax.Array) -> jnp.ndarray:
    """Random-Token-Selection priority rank (reference ``sharded_moe.py:234``
    ``use_rts``): which tokens win an expert's capacity slots is decided by a
    uniform draw rather than sequence position, so truncation under overflow
    is unbiased w.r.t. position. Returns per-token rank within its expert
    ``[G,S,E]`` (0 = first slot); unselected tokens rank last.

    The reference scatters ``_top_idx(mask * uniform, capacity)``; the XLA
    formulation is a double argsort over the (static) S axis — ranks are the
    positions each token would occupy in a random ordering of that expert's
    selected tokens."""
    r = jax.random.uniform(rng, mask.shape, minval=1e-6, maxval=1.0) * mask
    order = jnp.argsort(-r, axis=1)                         # tokens by priority
    return jnp.argsort(order, axis=1).astype(jnp.float32)   # rank of each token


def topk_gating(logits: jnp.ndarray, k: int, capacity: int,
                rng: Optional[jax.Array] = None,
                noisy_gate_policy: Optional[str] = None,
                drop_tokens: bool = True,
                norm_topk: bool = True,
                used_token: Optional[jnp.ndarray] = None,
                use_rts: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Generic top-k gating with capacity (covers reference top1/top2/topk).

    ``used_token [G,S]``: 0/1 mask excluding (padding) tokens from dispatch
    and from the aux-loss assigned fraction (reference ``top1gating:186``).
    ``use_rts``: Random Token Selection — capacity truncation picks winners
    by a uniform draw instead of sequence position (reference ``:234``);
    needs ``rng``, silently positional otherwise (deterministic eval).
    ``drop_tokens=False`` keeps every assignment; pass ``capacity >= k*S``
    (the static no-drop bound) or positions overflow silently.

    Returns (dispatch [G,S,E,C] bool, combine [G,S,E,C] f32, aux_loss scalar).
    """
    g, s, e = logits.shape
    logits = logits.astype(jnp.float32)
    rng_noise = rng_rts = None
    if rng is not None:
        rng_noise, rng_rts = jax.random.split(rng)
    # RSample perturbs expert SELECTION only (reference top1gating:156 uses
    # logits_w_noise = logits + gumbel for the argmax while gates/aux stay on
    # the clean softmax) — combine weights and the load-balance loss must not
    # see the noise or training dynamics drift.
    sel_logits = logits
    if noisy_gate_policy == "RSample" and rng_noise is not None:
        sel_logits = logits + jax.random.gumbel(rng_noise, logits.shape)
    gates = jax.nn.softmax(logits, axis=-1)  # [G,S,E] clean
    sel = gates if sel_logits is logits else jax.nn.softmax(sel_logits, axis=-1)
    aux_loss = load_balance_aux(gates, used_token,
                                sel_gates=None if sel_logits is logits else sel)
    ut = None if used_token is None else used_token.astype(jnp.float32)

    remaining = sel
    committed = jnp.zeros((g, 1, e), jnp.float32)  # tokens assigned per expert so far
    dispatch = jnp.zeros((g, s, e, capacity), jnp.bool_)
    combine = jnp.zeros((g, s, e, capacity), jnp.float32)
    denom = jnp.zeros((g, s), jnp.float32)

    for ki in range(k):
        idx = jnp.argmax(remaining, axis=-1)                # [G,S]
        mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)    # [G,S,E]
        if ut is not None:  # padding tokens never occupy a slot
            mask = mask * ut[..., None]
        gate_k = jnp.sum(gates * mask, axis=-1)             # [G,S]
        if use_rts and rng_rts is not None and drop_tokens:
            # random slot priority within each expert; committed offsets the
            # later rounds the same way the positional path does
            rank = _rts_rank(mask, jax.random.fold_in(rng_rts, ki))
            pos_in_expert = rank + committed                # [G,S,E]
        else:
            # capacity slot = tokens assigned to this expert earlier in this
            # round + total committed in previous rounds (reference top2gating
            # locations2 offset by sum(mask1))
            pos_in_expert = jnp.cumsum(mask, axis=1) - mask + committed
        pos = jnp.sum(pos_in_expert * mask, axis=-1)        # [G,S]
        keep = pos < capacity
        if not drop_tokens:
            keep = jnp.sum(mask, axis=-1) > 0  # selected and not padding
        pos_c = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        slot = mask[..., None] * pos_c[:, :, None, :] * keep[:, :, None, None]  # [G,S,E,C]
        dispatch = dispatch | (slot > 0)
        combine = combine + slot * gate_k[:, :, None, None]
        denom = denom + gate_k * keep
        committed = committed + jnp.sum(mask, axis=1, keepdims=True)
        remaining = remaining * (1.0 - mask)

    if norm_topk:
        # renormalize combine weights over the k selected experts (reference
        # top2gating denominator; qwen2_moe norm_topk_prob=False skips this)
        combine = combine / jnp.maximum(denom, 1e-9)[:, :, None, None]
    return dispatch, combine, aux_loss


def moe_dispatch(x: jnp.ndarray, dispatch: jnp.ndarray) -> jnp.ndarray:
    """tokens [G,S,D] x dispatch [G,S,E,C] -> expert inputs [E, G, C, D].
    Expert-major layout so the 'ep' sharding sits on dim 0."""
    return jnp.einsum("gsec,gsd->egcd", dispatch.astype(x.dtype), x)


def expert_ffn(expert_in: jnp.ndarray, w_up, w_down, *, w_gate=None,
               b_up=None, b_down=None, b_gate=None,
               activation: str = "swiglu") -> jnp.ndarray:
    """Expert-major FFN on ``[E, G, C, D]`` inputs — the ONE definition of
    the per-expert compute, shared by the declarative capacity path
    (``moe/layer.py``) and the explicit int8 EP path
    (:func:`quantized_ep_moe`) so the two branches cannot drift."""
    dt = expert_in.dtype
    u = jnp.einsum("egcd,edf->egcf", expert_in, w_up.astype(dt))
    if b_up is not None:
        u = u + b_up.astype(dt)[:, None, None, :]
    if activation == "swiglu":
        h = jnp.einsum("egcd,edf->egcf", expert_in, w_gate.astype(dt))
        if b_gate is not None:
            h = h + b_gate.astype(dt)[:, None, None, :]
        h = jax.nn.silu(h) * u
    else:
        h = jax.nn.gelu(u)
    out = jnp.einsum("egcf,efd->egcd", h, w_down.astype(dt))
    if b_down is not None:
        out = out + b_down.astype(dt)[:, None, None, :]
    return out


def quantized_ep_ready(num_experts: int, num_groups: Optional[int] = None,
                       site_shape: Optional[Tuple[int, ...]] = None,
                       site_dtype=None) -> bool:
    """True when the explicit int8 EP exchange applies: a real ep axis the
    experts split evenly over, full sequences rank-local (sp == 1 — the
    dispatch slot einsum is exact only over the whole S axis), token groups
    that shard evenly over the data axes (shard_map hard-requires the
    divisibility the declarative constraints merely prefer), and the MoE
    site switched on — by the ``compressed_collectives`` knob when that is
    explicitly configured, else by the collective planner (``comm/planner``
    mode static|measure) resolving the moe-a2a site (``site_shape`` /
    ``site_dtype`` describe the dispatch tensor the exchange would carry)."""
    from ..comm.compressed import compression_mode
    from ..parallel.topology import EP_AXIS, get_topology

    # inside an enclosing shard_map (e.g. the SPMD pipeline body) the mesh
    # axes are manual and a nested shard_map cannot open — declarative path
    from ..utils.shard_map_compat import manual_axes

    if manual_axes():
        return False
    topo = get_topology()
    if num_groups is not None and num_groups % (topo.dp_outer_size
                                                * topo.ep_size) != 0:
        return False
    if not (topo.ep_size > 1 and topo.sp_size == 1
            and num_experts % topo.ep_size == 0):
        return False
    if compression_mode() != "none":  # raw knob set (incl. site toggles)
        return compression_mode("moe") != "none"
    from ..comm.planner import planner_active, resolve_site

    if not planner_active():
        return False
    d = resolve_site(op="all_to_all",
                     shape=site_shape or (num_experts,),
                     dtype=site_dtype or "float32",
                     axes=(EP_AXIS,), consumer="moe-a2a")
    return d.impl in ("int8", "int8_sr")


def quantized_ep_moe(x, dispatch, combine, w_up, w_down, *, w_gate=None,
                     b_up=None, b_down=None, b_gate=None,
                     activation: str = "swiglu") -> jnp.ndarray:
    """Capacity-path MoE with the EP dispatch/combine exchange carried as
    int8 all-to-alls (``comm/compressed.py``).

    The declarative path hands XLA the expert-major sharding constraint and
    lets the partitioner insert EXACT all-to-alls for the token->expert
    resharding; this runs the same exchange explicitly inside ``shard_map``
    with quantized payloads — ~4x fewer EP-link bytes each way:

      local dispatch einsum -> [E, G_l, C, D] full-E
      quantized all-to-all (split E, concat tokens) -> [E/ep, G_l*ep, C, D]
      expert FFN on local experts
      quantized all-to-all back (split tokens, concat E) -> [E, G_l, C, D]
      local combine einsum -> [G_l, S, D]

    Backward rides the exchanges' straight-through vjp (exact transposed
    all-to-alls). Callers check :func:`quantized_ep_ready` first.
    """
    from ..comm.compressed import quantized_all_to_all
    from ..parallel.topology import EP_AXIS, get_topology
    from ..sharding import sites
    from ..utils.shard_map_compat import shard_map_nocheck

    topo = get_topology()
    tok = sites.moe_batch_act(3, ep_axis=EP_AXIS)
    tok4 = sites.moe_batch_act(4, ep_axis=EP_AXIS)
    exp_w = sites.moe_expert_weight(EP_AXIS)
    args = [x, dispatch, combine, w_up, w_down]
    specs = [tok, tok4, tok4, exp_w, exp_w]
    flags = []
    for name, val in (("gate", w_gate), ("b_up", b_up), ("b_down", b_down),
                      ("b_gate", b_gate)):
        if val is not None:
            flags.append(name)
            args.append(val)
            specs.append(exp_w)

    def body(x_, d_, c_, wu_, wd_, *rest):
        opt = dict(zip(flags, rest))
        ei = moe_dispatch(x_, d_)                            # [E, G_l, C, D]
        ei = quantized_all_to_all(ei, EP_AXIS, split_dim=0, concat_dim=1)
        out = expert_ffn(ei, wu_, wd_, w_gate=opt.get("gate"),
                         b_up=opt.get("b_up"), b_down=opt.get("b_down"),
                         b_gate=opt.get("b_gate"), activation=activation)
        out = quantized_all_to_all(out, EP_AXIS, split_dim=1, concat_dim=0)
        return moe_combine(out, c_)                          # [G_l, S, D]

    return shard_map_nocheck(body, topo.mesh, in_specs=tuple(specs),
                             out_specs=tok)(*args)


def moe_combine(expert_out: jnp.ndarray, combine: jnp.ndarray) -> jnp.ndarray:
    """expert outputs [E,G,C,D] x combine [G,S,E,C] -> tokens [G,S,D]."""
    return jnp.einsum("egcd,gsec->gsd", expert_out, combine.astype(expert_out.dtype))


def dropless_moe(x: jnp.ndarray, gates: jnp.ndarray, k: int,
                 w_gate: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
                 activation: str = "swiglu",
                 norm_topk: bool = True,
                 b_up: jnp.ndarray = None, b_down: jnp.ndarray = None,
                 b_gate: jnp.ndarray = None) -> jnp.ndarray:
    """Dropless MoE via grouped GEMM (``jax.lax.ragged_dot``).

    TPU-native replacement for the reference CUTLASS grouped ``moe_gemm``
    (``inference/v2/kernels/cutlass_ops/moe_gemm/``) and the megablocks-style
    dropless path: every token reaches its top-k experts (no capacity, no
    zero-padded compute). Tokens are sorted by expert id; ``ragged_dot``
    multiplies each contiguous group against its expert's weights on the MXU
    without materializing per-expert padding.

    x: [G, S, D]; gates: [G, S, E] fp32 router probabilities;
    w_gate/w_up: [E, D, F]; w_down: [E, F, D]. Returns [G, S, D].
    """
    g, s, d = x.shape
    e = gates.shape[-1]
    n = g * s
    xf = x.reshape(n, d)
    gf = gates.reshape(n, e)

    top_w, top_e = jax.lax.top_k(gf, k)                     # [N, k]
    if norm_topk:
        top_w = top_w / jnp.maximum(jnp.sum(top_w, axis=-1, keepdims=True), 1e-9)
    eid = top_e.reshape(-1)                                 # [N*k]
    wts = top_w.reshape(-1)                                 # [N*k]
    order = jnp.argsort(eid)                                # expert-sorted copies
    tok_of = order // k                                     # source token per copy
    xs = xf[tok_of]                                         # [N*k, D]
    group_sizes = jnp.bincount(eid, length=e).astype(jnp.int32)

    wu = w_up.astype(x.dtype)
    wd = w_down.astype(x.dtype)
    eid_sorted = eid[order]                                 # expert per row
    up = jax.lax.ragged_dot(xs, wu, group_sizes)
    if b_up is not None:  # megatron-MoE experts carry biases
        up = up + b_up.astype(x.dtype)[eid_sorted]
    if activation == "swiglu":
        wg = w_gate.astype(x.dtype)
        gt = jax.lax.ragged_dot(xs, wg, group_sizes)
        if b_gate is not None:
            gt = gt + b_gate.astype(x.dtype)[eid_sorted]
        h = jax.nn.silu(gt) * up
    else:  # w_gate is None for ungated activations
        h = jax.nn.gelu(up)
    out = jax.lax.ragged_dot(h, wd, group_sizes)            # [N*k, D]
    if b_down is not None:
        out = out + b_down.astype(x.dtype)[eid_sorted]

    out = out * wts[order][:, None].astype(out.dtype)
    yf = jnp.zeros((n, d), out.dtype).at[tok_of].add(out)
    return yf.reshape(g, s, d)
