"""MoE block module (reference ``MoE``, ``deepspeed/moe/layer.py:17`` +
``MOELayer``, ``sharded_moe.py:533``).

Expert parallelism TPU-style: expert weights are stacked ``[E, ...]`` arrays
sharded over the ``ep`` mesh axis (see ``models/transformer.py::param_specs``);
dispatching tokens to experts is an einsum into expert-major layout with a
sharding constraint, which XLA lowers to the same all-to-all pattern the
reference issues via ``_AllToAll`` (``sharded_moe.py:96``). Expert-vs-dense
gradient separation (reference ``engine._reduce_expert_gradients:2510``) is
automatic: expert params are sharded over ``ep``, so SPMD autodiff reduces
their grads only over the remaining data axes.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..sharding import sites
from .sharded_moe import (compute_capacity, dropless_moe, expert_ffn,
                          load_balance_aux, moe_combine, moe_dispatch,
                          quantized_ep_moe, quantized_ep_ready, topk_gating)


def _constrain(x, spec, skip: bool = False):
    """Sharding constraint on the dispatch layout. ``skip`` during flax init,
    where trace shapes need not divide the mesh. Per-dimension, the constraint
    is dropped (→ replicated) when the dim doesn't divide its mesh axes — e.g.
    tiny inference batches over a large dp axis."""
    if skip:
        return x
    from ..parallel.topology import get_topology

    topo = get_topology()
    if topo.n_devices > 1:
        # inside shard_map (e.g. the SPMD pipeline body) the mesh axes are
        # manual: per-shard values carry no global sharding to constrain —
        # layout is already fixed by the enclosing in_specs
        get_am = getattr(jax.sharding, "get_abstract_mesh", None)  # jax>=0.5
        manual = getattr(get_am(), "manual_axes", ()) if get_am else ()
        axes_in_spec = {a for entry in spec if entry is not None
                        for a in (entry if isinstance(entry, tuple) else (entry,))}
        if axes_in_spec & set(manual):
            return x
        eff = topo.filter_spec(spec, x.shape)
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(topo.mesh, eff))
    return x


class MoEBlock(nn.Module):
    """Drop-in MLP replacement returning ``(out, aux_loss)``.

    The per-expert token counts diagnostic (reference ``MoE.forward``'s third
    return, ``exp_counts``) is sown as the ``moe_exp_counts`` intermediate:
    PRE-capacity router assignments with padding tokens excluded — matching
    the reference (``top1gating`` computes exp_counts from ``mask1`` before
    the capacity truncation) and identical semantics on both the capacity
    and dropless paths.

    ``used_token [G,S]`` (reference ``MoE.forward(hidden, used_token)``,
    ``moe/layer.py:115``) excludes padding tokens from dispatch + aux loss.
    Gating stochasticity (RSample / Jitter noise, Random Token Selection)
    draws from the ``"gating"`` rng collection when the caller provides one
    (``model.apply(..., rngs={"gating": key})``); without it gating is
    deterministic — eval and tracing stay reproducible.
    """
    cfg: object  # TransformerConfig

    def _sow_exp_counts(self, gates, k, e, used_token):
        """Pre-drop per-expert assignment counts (see class docstring)."""
        _, top_e = jax.lax.top_k(gates, k)                   # [G, S, k]
        hot = jax.nn.one_hot(top_e, e, dtype=jnp.int32)      # [G, S, k, E]
        if used_token is not None:
            hot = hot * used_token.astype(jnp.int32)[..., None, None]
        self.sow("intermediates", "moe_exp_counts",
                 jnp.sum(hot, axis=(0, 1, 2)))

    @nn.compact
    def __call__(self, x, used_token=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.cfg
        g, s, d = x.shape
        e, k = cfg.num_experts, cfg.moe_top_k
        f = cfg.moe_intermediate_size or cfg.intermediate_size
        drop_tokens = getattr(cfg, "moe_drop_tokens", True)
        if drop_tokens:
            capacity = compute_capacity(k, s, e, cfg.moe_capacity_factor)
        else:
            # static no-drop bound (the reference grows capacity dynamically,
            # sharded_moe.py:214 — a data-dependent shape XLA can't trace;
            # k*S is its worst case. moe_dropless is the efficient no-drop.)
            capacity = k * s
        gate_rng = (self.make_rng("gating")
                    if not self.is_initializing() and self.has_rng("gating") else None)
        noisy = getattr(cfg, "moe_noisy_gate_policy", None)

        # router in fp32 (reference TopKGate keeps the gate fp32)
        router = nn.Dense(e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")
        x_router = x.astype(jnp.float32)
        if noisy == "Jitter" and gate_rng is not None:
            # reference TopKGate jitters the router INPUT (sharded_moe.py:431)
            jit_rng, gate_rng = jax.random.split(gate_rng)
            x_router = x_router * jax.random.uniform(
                jit_rng, x_router.shape, minval=0.99, maxval=1.01)
        logits = router(x_router)

        init = nn.initializers.lecun_normal()
        swiglu = cfg.activation == "swiglu"
        # gate projection exists only for gated activations (mirrors MLP)
        w_gate = (self.param("expert_gate_proj", init, (e, d, f), jnp.float32)
                  if swiglu else None)
        w_up = self.param("expert_up_proj", init, (e, d, f), jnp.float32)
        w_down = self.param("expert_down_proj", init, (e, f, d), jnp.float32)
        # expert biases (megatron-MoE ParallelMLP experts carry them; the
        # llama-family MoEs do not) follow the dense-MLP bias heuristic
        zeros = nn.initializers.zeros
        b_up = (self.param("expert_up_bias", zeros, (e, f), jnp.float32)
                if cfg.ffn_bias else None)
        b_down = (self.param("expert_down_bias", zeros, (e, d), jnp.float32)
                  if cfg.ffn_bias else None)
        b_gate = (self.param("expert_gate_bias", zeros, (e, f), jnp.float32)
                  if cfg.ffn_bias and swiglu else None)
        skip = self.is_initializing()

        norm_topk = cfg.moe_norm_topk

        # qwen2_moe always-on shared expert, modulated by a sigmoid gate
        fs = cfg.moe_shared_expert_size
        if fs:
            sg = self.param("shared_gate_proj", init, (d, fs), jnp.float32)
            su = self.param("shared_up_proj", init, (d, fs), jnp.float32)
            sdn = self.param("shared_down_proj", init, (fs, d), jnp.float32)
            srt = self.param("shared_router", init, (d, 1), jnp.float32)

        # PR-MoE residual (reference MoE.forward, moe/layer.py:124): a dense
        # MLP runs beside the experts; a learned per-token 2-way softmax
        # coefficient blends them. Distinct from qwen2's shared expert
        # (sigmoid-modulated ADDITION) below.
        use_residual = getattr(cfg, "moe_use_residual", False)
        if use_residual:
            r_up = self.param("residual_up_proj", init, (d, f), jnp.float32)
            r_down = self.param("residual_down_proj", init, (f, d), jnp.float32)
            r_gate = (self.param("residual_gate_proj", init, (d, f), jnp.float32)
                      if swiglu else None)
            r_coef = self.param("residual_coefficient", init, (d, 2), jnp.float32)

        def add_residual(y):
            if not use_residual:
                return y
            if swiglu:
                h_r = nn.silu(x @ r_gate.astype(x.dtype)) * (x @ r_up.astype(x.dtype))
            else:
                h_r = nn.gelu(x @ r_up.astype(x.dtype))
            out_r = h_r @ r_down.astype(x.dtype)
            coef = nn.softmax((x.astype(jnp.float32) @ r_coef), axis=-1)
            coef = coef.astype(y.dtype)
            return y * coef[..., 0:1] + out_r * coef[..., 1:2]

        def add_shared(y):
            y = add_residual(y)
            if not fs:
                return y
            h_s = nn.silu(x @ sg.astype(x.dtype)) * (x @ su.astype(x.dtype))
            out_s = h_s @ sdn.astype(x.dtype)
            mod = nn.sigmoid((x.astype(jnp.float32) @ srt)).astype(x.dtype)
            return y + out_s * mod

        if getattr(cfg, "moe_dropless", False):
            # grouped-GEMM dropless path (reference cutlass moe_gemm /
            # megablocks): no capacity, no zero-padded compute. Token
            # grouping is a global sort under SPMD, so this path shines for
            # ep=1 (local groups); with ep>1 prefer the capacity einsums.
            gates = jax.nn.softmax(logits, axis=-1)
            aux = load_balance_aux(gates, used_token)
            self._sow_exp_counts(gates, k, e, used_token)
            y = dropless_moe(x, gates, k, w_gate, w_up, w_down,
                             activation=cfg.activation, norm_topk=norm_topk,
                             b_up=b_up, b_down=b_down, b_gate=b_gate)
            if used_token is not None:  # padding tokens contribute nothing
                y = y * used_token.astype(y.dtype)[..., None]
            y = add_shared(y.astype(x.dtype))
            y = _constrain(y, sites.moe_batch_act(3), skip)
            return y.astype(x.dtype), aux * cfg.moe_aux_loss_weight

        dispatch, combine, aux = topk_gating(
            logits, k, capacity, rng=gate_rng,
            noisy_gate_policy=noisy if noisy == "RSample" else None,
            drop_tokens=drop_tokens, norm_topk=norm_topk,
            used_token=used_token,
            use_rts=getattr(cfg, "moe_use_rts", True))
        # keep the token-major mask sharded like the activations (G over
        # dp, S over sp): leaving it unconstrained made the partitioner
        # replicate-and-repartition the dispatch collective-permute
        # ("involuntary full rematerialization", spmd_partitioner.cc:652)
        tok_mask_spec = sites.moe_batch_act(4, sp_axis="sp")
        dispatch = _constrain(dispatch, tok_mask_spec, skip)
        combine = _constrain(combine, tok_mask_spec, skip)

        self._sow_exp_counts(jax.nn.softmax(logits, axis=-1), k, e, used_token)

        if not skip and quantized_ep_ready(e, g, site_shape=(e, g, capacity, d),
                                           site_dtype=x.dtype):
            # compressed_collectives / comm-planner MoE site: the EP
            # dispatch/combine exchange runs explicitly with int8 payloads
            # (sharded_moe.py quantized_ep_moe) instead of the partitioner's
            # exact a2a
            y = quantized_ep_moe(
                x, dispatch, combine, w_up, w_down, w_gate=w_gate,
                b_up=b_up, b_down=b_down, b_gate=b_gate,
                activation=cfg.activation)
        else:
            # expert-major dispatch: [E, G, C, D], experts over the ep axis
            expert_in = moe_dispatch(x, dispatch)
            expert_in = _constrain(expert_in, sites.moe_expert_major_act(4), skip)
            out = expert_ffn(expert_in, w_up, w_down, w_gate=w_gate,
                             b_up=b_up, b_down=b_down, b_gate=b_gate,
                             activation=cfg.activation)
            out = _constrain(out, sites.moe_expert_major_act(4), skip)

            y = moe_combine(out, combine)
        y = add_shared(y.astype(x.dtype))
        y = _constrain(y, sites.moe_batch_act(3, sp_axis="sp"), skip)
        return y.astype(x.dtype), aux * cfg.moe_aux_loss_weight
