from .logging import log_dist, logger
from .memory import (compiled_memory_analysis, memory_status,
                     see_memory_usage)
from .tensor_fragment import (safe_get_full_fp32_param, safe_get_full_grad,
                              safe_get_full_optimizer_state,
                              safe_get_local_fp32_param, safe_get_local_grad,
                              safe_get_local_optimizer_state,
                              safe_set_full_fp32_param, safe_set_full_grad,
                              safe_set_full_optimizer_state,
                              safe_set_local_fp32_param, safe_set_local_grad,
                              safe_set_local_optimizer_state)

__all__ = ["log_dist", "logger", "see_memory_usage", "memory_status",
           "compiled_memory_analysis",
           "safe_get_full_fp32_param", "safe_set_full_fp32_param",
           "safe_get_full_grad", "safe_set_full_grad",
           "safe_get_full_optimizer_state", "safe_set_full_optimizer_state",
           "safe_get_local_fp32_param", "safe_set_local_fp32_param",
           "safe_get_local_grad", "safe_set_local_grad",
           "safe_get_local_optimizer_state", "safe_set_local_optimizer_state"]
