from .logging import log_dist, logger
from .memory import (compiled_memory_analysis, memory_status,
                     see_memory_usage)

__all__ = ["log_dist", "logger", "see_memory_usage", "memory_status",
           "compiled_memory_analysis"]
