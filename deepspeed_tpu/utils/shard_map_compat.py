"""shard_map / axis-introspection version shims.

jax >= 0.8 exposes ``jax.shard_map`` with a ``check_vma`` kwarg; older
releases have ``jax.experimental.shard_map.shard_map`` with ``check_rep``.
One probe, shared by every explicit-collective module (onebit, zeropp,
tests) so the version logic cannot drift between copies. ``axis_size``
shims ``lax.axis_size`` (jax >= 0.5) onto the classic ``psum(1, axis)``
spelling the same way.
"""

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_NOCHECK_KW = ({"check_vma": False}
               if "check_vma" in inspect.signature(_shard_map).parameters
               else {"check_rep": False})


def shard_map_nocheck(fn, mesh, in_specs, out_specs):
    """shard_map with the replication/vma check disabled (whichever kwarg the
    installed jax spells it with)."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **_NOCHECK_KW)


def shard_map(fn, mesh, in_specs, out_specs, **kw):
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis) -> int:
    """Size of a mesh axis from inside shard_map — ``lax.axis_size`` where
    it exists, else the trace-time-static ``psum(1, axis)`` spelling."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis))
    return int(lax.psum(1, axis))


def manual_axes() -> frozenset:
    """Mesh axes currently bound manual (i.e. tracing inside a shard_map).
    Callers that would NEST a shard_map (the collective-matmul overlap
    wiring) must stay on the declarative path when this is non-empty.
    New jax tracks it on the abstract mesh; old jax exposes the bound
    axis env (private but stable across the 0.4.x line)."""
    import jax

    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_am is not None:
        return frozenset(getattr(get_am(), "manual_axes", ()) or ())
    try:
        from jax._src.core import get_axis_env

        return frozenset(get_axis_env().axis_sizes)
    except Exception:  # pragma: no cover - future jax: fail open (no axes)
        return frozenset()


def shard_map_nocheck_manual(fn, mesh, in_specs, out_specs, axis_names):
    """``shard_map_nocheck`` with an explicit manual-axes set: new jax
    spells it ``axis_names=<manual>``, old jax as the complement
    ``auto=<all - manual>`` — translated here so callers write one form."""
    kw = dict(_NOCHECK_KW)
    if "check_vma" in _NOCHECK_KW:  # jax >= 0.8: native axis_names kwarg
        kw["axis_names"] = set(axis_names)
    else:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)
