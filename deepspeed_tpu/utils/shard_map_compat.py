"""shard_map version shim.

jax >= 0.8 exposes ``jax.shard_map`` with a ``check_vma`` kwarg; older
releases have ``jax.experimental.shard_map.shard_map`` with ``check_rep``.
One probe, shared by every explicit-collective module (onebit, zeropp,
tests) so the version logic cannot drift between copies.
"""

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_NOCHECK_KW = ({"check_vma": False}
               if "check_vma" in inspect.signature(_shard_map).parameters
               else {"check_rep": False})


def shard_map_nocheck(fn, mesh, in_specs, out_specs):
    """shard_map with the replication/vma check disabled (whichever kwarg the
    installed jax spells it with)."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **_NOCHECK_KW)


def shard_map(fn, mesh, in_specs, out_specs, **kw):
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
