"""Accelerator health probe.

A remote-attached TPU whose tunnel is wedged HANGS on first use rather than
failing; probing in a subprocess with a hard timeout lets callers (bench.py,
``__graft_entry__.py``, the launcher's elastic rescale hook) fall back to
CPU instead of hanging forever.

The timeout defaults to ``$DSTPU_HEALTH_TIMEOUT`` seconds (180 when unset)
so fleets with slow tunnels — or CI that wants instant verdicts — tune every
probe site with one env var instead of chasing hardcoded constants. A
timeout of 0 (or negative) reports unhealthy immediately without spawning
the probe at all.
"""

import os
import subprocess
import sys
from typing import Optional

DEFAULT_TIMEOUT_S = 180.0
TIMEOUT_ENV = "DSTPU_HEALTH_TIMEOUT"


def health_timeout_s(default: float = DEFAULT_TIMEOUT_S) -> float:
    """The probe timeout: ``$DSTPU_HEALTH_TIMEOUT`` when set and parseable,
    else ``default``."""
    raw = os.environ.get(TIMEOUT_ENV)
    if raw is None or raw.strip() == "":
        return float(default)
    try:
        return float(raw)
    except ValueError:
        return float(default)


_PROBE = ("import jax, jax.numpy as jnp;"
          "y = jax.jit(lambda a: a @ a)(jnp.ones((256, 256), jnp.bfloat16));"
          "jax.block_until_ready(y); print('ok')")


def accelerator_healthy(timeout_s: Optional[float] = None) -> bool:
    """Whether the default jax backend completes a tiny jitted matmul within
    the timeout (any platform counts as healthy; only a hang/crash fails).
    ``timeout_s=None`` resolves via :func:`health_timeout_s`; a non-positive
    timeout reports unhealthy without probing (so a 0-second budget cannot
    hang)."""
    t = health_timeout_s() if timeout_s is None else float(timeout_s)
    if t <= 0:
        return False
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE],
                           capture_output=True, text=True, timeout=t)
        return r.returncode == 0 and r.stdout.strip().endswith("ok")
    except subprocess.TimeoutExpired:
        return False


_COUNT_PROBE = "import jax; print(jax.device_count())"


def accelerator_device_count(timeout_s: Optional[float] = None) -> int:
    """Device count of the default backend, probed in a subprocess so the
    CALLER never initializes the backend (same rationale as
    ``accelerator_healthy``: a parent that touches the TPU holds it
    exclusively and starves its child processes). 0 on hang/crash or a
    non-positive timeout."""
    t = health_timeout_s() if timeout_s is None else float(timeout_s)
    if t <= 0:
        return 0
    try:
        r = subprocess.run([sys.executable, "-c", _COUNT_PROBE],
                           capture_output=True, text=True, timeout=t)
        if r.returncode != 0:
            return 0
        return int(r.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return 0
