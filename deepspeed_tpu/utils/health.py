"""Accelerator health probe.

A remote-attached TPU whose tunnel is wedged HANGS on first use rather than
failing; probing in a subprocess with a hard timeout lets callers (bench.py,
__graft_entry__.py) fall back to CPU instead of hanging forever.
"""

import subprocess
import sys

_PROBE = ("import jax, jax.numpy as jnp;"
          "y = jax.jit(lambda a: a @ a)(jnp.ones((256, 256), jnp.bfloat16));"
          "jax.block_until_ready(y); print('ok')")


def accelerator_healthy(timeout_s: int = 180) -> bool:
    """Whether the default jax backend completes a tiny jitted matmul within
    ``timeout_s`` (any platform counts as healthy; only a hang/crash fails)."""
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE],
                           capture_output=True, text=True, timeout=timeout_s)
        return r.returncode == 0 and r.stdout.strip().endswith("ok")
    except subprocess.TimeoutExpired:
        return False


_COUNT_PROBE = "import jax; print(jax.device_count())"


def accelerator_device_count(timeout_s: int = 180) -> int:
    """Device count of the default backend, probed in a subprocess so the
    CALLER never initializes the backend (same rationale as
    ``accelerator_healthy``: a parent that touches the TPU holds it
    exclusively and starves its child processes). 0 on hang/crash."""
    try:
        r = subprocess.run([sys.executable, "-c", _COUNT_PROBE],
                           capture_output=True, text=True, timeout=timeout_s)
        if r.returncode != 0:
            return 0
        return int(r.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return 0
