"""Comms ledger: per-op counts/sizes/latency/bandwidth.

Reference: ``CommsLogger`` (``deepspeed/utils/comms_logging.py:67``) and the
``timed_op`` wrapper (``comm/comm.py:101``). On TPU, collectives issued inside
``jit`` are fused by XLA and cannot be individually timed at run time; instead
we record them at **trace time** (shapes are static, so message sizes are
exact) and time eager ops for real. ``log_summary`` prints the same
count/size/latency/algbw/busbw table the reference does.
"""

import time
from collections import defaultdict
from typing import Any, Dict, List, Optional


def get_msg_size(nbytes: int) -> str:
    for unit in ["B", "KB", "MB", "GB", "TB"]:
        if nbytes < 1024:
            return f"{nbytes:.2f} {unit}"
        nbytes /= 1024
    return f"{nbytes:.2f} PB"


def calc_bw(op_name: str, size_bytes: int, duration_s: float, n: int):
    """Algorithm / bus bandwidth in GB/s (NCCL-tests conventions, as in the
    reference ``comms_logging.get_bw``)."""
    if duration_s <= 0:
        return 0.0, 0.0
    algbw = size_bytes / duration_s / 1e9
    if "all_to_all" in op_name:
        busbw = algbw * ((n - 1) / n)
    elif "all_gather" in op_name or "reduce_scatter" in op_name:
        busbw = algbw * ((n - 1) / n)
    elif "all_reduce" in op_name:
        busbw = algbw * (2 * (n - 1) / n)
    else:  # broadcast, send/recv, barrier
        busbw = algbw
    return algbw, busbw


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False, debug: bool = False,
                 prof_all: bool = True, prof_ops: Optional[List[str]] = None):
        self.enabled = enabled
        self.verbose = verbose
        self.debug = debug
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        # op_name -> msg_size -> [count, total_latency_s, traced_count, wire_bytes_total]
        # msg_size is the LOGICAL payload (what the exact collective would
        # move); wire_bytes_total accumulates what actually rides the links —
        # compressed collectives report int8 payload + scale lanes there.
        self.comms_dict: Dict[str, Dict[int, List[float]]] = defaultdict(
            lambda: defaultdict(lambda: [0, 0.0, 0, 0]))
        # hop class ("ici" | "dcn" | "host") -> accumulated wire bytes:
        # multi-phase collective programs tag each phase with the link class
        # its traffic rides (comm/planner ir.PhaseStep.link), so the ledger
        # can answer "how many bytes crossed the slice boundary" directly
        self.hop_bytes: Dict[str, int] = defaultdict(int)
        # the HIDDEN subset of hop_bytes: wire bytes whose transfer rides
        # behind compute (via="fused_matmul" phases — the ppermute hops
        # interleave with the bound matmul's tiles). hop_exposure() reports
        # exposed = total - hidden per link class; the t3 bench gates on
        # the exposed fraction dropping when programs fuse
        self.hop_hidden_bytes: Dict[str, int] = defaultdict(int)
        # site signature -> planner decision info (comm/planner): per-mesh
        # facts, not per-step counters — reset() deliberately keeps them
        self.plan_records: Dict[str, Dict[str, Any]] = {}
        # executable label -> compile-time memory_analysis breakdown
        # (runtime/engine records these when a step compiles); per-program
        # facts like plan_records, so reset() keeps them too
        self.memory_records: Dict[str, Dict[str, Any]] = {}
        # executable label -> static-audit summary (deepspeed_tpu/analysis,
        # recorded by the engine's compile-time hook); per-program facts —
        # reset() keeps them
        self.analysis_records: Dict[str, Dict[str, Any]] = {}

    def configure(self, enabled=None, verbose=None, prof_all=None, prof_ops=None, debug=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops
        if debug is not None:
            self.debug = debug

    def _should_log(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, size_bytes: int, latency_s: float = 0.0, traced: bool = False,
               wire_bytes: Optional[int] = None, hop_class: Optional[str] = None,
               hop_hidden: bool = False):
        """``wire_bytes`` defaults to ``size_bytes`` (exact collectives move
        what they carry); compressed collectives pass the smaller on-wire
        total so the ledger can report the compression ratio. ``hop_class``
        additionally buckets the wire bytes by link class (ici/dcn/host) —
        only hop-aware callers (program phases) pass it. ``hop_hidden``
        marks the hop-classed bytes as compute-overlapped (fused phases):
        they still count in ``hop_totals`` but ``hop_exposure`` subtracts
        them from the exposed side."""
        if not self._should_log(op_name):
            return
        rec = self.comms_dict[op_name][size_bytes]
        rec[0] += 1
        rec[1] += latency_s
        rec[2] += 1 if traced else 0
        rec[3] += int(size_bytes if wire_bytes is None else wire_bytes)
        if hop_class is not None:
            w = int(size_bytes if wire_bytes is None else wire_bytes)
            self.hop_bytes[hop_class] += w
            if hop_hidden:
                self.hop_hidden_bytes[hop_class] += w
        if self.verbose:
            from .logging import logger

            kind = "traced" if traced else f"{latency_s*1e3:.2f} ms"
            logger.info(f"comm op: {op_name} | size: {get_msg_size(size_bytes)} | {kind}")

    def record_plan(self, signature: str, info: Dict[str, Any]) -> None:
        """Record one resolved planner decision (``comm/planner``). Stored
        unconditionally — plan facts are cheap and ``log_summary`` prints
        them as the plan table; unlike traffic rows they survive
        ``reset()`` (the plan is per-topology, not per-step)."""
        self.plan_records[signature] = dict(info)

    def record_memory(self, label: str, info: Dict[str, Any]) -> None:
        """Record one compiled executable's ``memory_analysis()`` breakdown
        (arg/output/temp/generated bytes) under a stable label — surfaced
        in the plan table and carried into flight dumps, so a post-mortem
        knows what the program *needed*, not just what the allocator held."""
        self.memory_records[label] = dict(info)

    def record_analysis(self, label: str, info: Dict[str, Any]) -> None:
        """Record one compiled step's static-audit summary (error/warning/
        info counts, unplanned-collective count) — surfaced in the plan
        table so ``log_summary`` shows the audit verdict next to the plan
        it was reconciled against."""
        self.analysis_records[label] = dict(info)

    def analysis_table_lines(self) -> List[str]:
        """The audit-verdict table (one row per audited step), empty when
        no audit has been recorded."""
        if not self.analysis_records:
            return []
        header = (f"{'Audited step':<24}{'Errors':<8}{'Warnings':<10}"
                  f"{'Info':<7}{'Unplanned':<11}{'Collectives':<12}")
        lines = ["Static audit (analysis):", header, "-" * len(header)]
        for label in sorted(self.analysis_records):
            r = self.analysis_records[label]
            lines.append(
                f"{label:<24}{r.get('error', 0):<8}{r.get('warning', 0):<10}"
                f"{r.get('info', 0):<7}"
                f"{r.get('unplanned_collectives', 0):<11}"
                f"{r.get('hlo_collectives', 0):<12}")
        return lines

    def memory_table_lines(self) -> List[str]:
        """The executable-memory table (one row per compiled step), empty
        when nothing has been recorded."""
        if not self.memory_records:
            return []
        header = (f"{'Executable':<24}{'Args(MB)':<11}{'Out(MB)':<10}"
                  f"{'Temp(MB)':<11}{'Code(KB)':<10}")
        lines = ["Executable memory (memory_analysis):", header,
                 "-" * len(header)]
        mb = 1024 * 1024
        for label in sorted(self.memory_records):
            r = self.memory_records[label]
            lines.append(
                f"{label:<24}"
                f"{r.get('argument_size_in_bytes', 0) / mb:<11.1f}"
                f"{r.get('output_size_in_bytes', 0) / mb:<10.1f}"
                f"{r.get('temp_size_in_bytes', 0) / mb:<11.1f}"
                f"{r.get('generated_code_size_in_bytes', 0) / 1024:<10.1f}")
        return lines

    def plan_table_lines(self) -> List[str]:
        """The resolved-plan table (one row per site, plus the executable
        memory and static-audit rows when a compiled step recorded them),
        empty when nothing has been recorded."""
        if not self.plan_records:
            lines = self.memory_table_lines()
            audit = self.analysis_table_lines()
            if audit:
                lines += ([""] if lines else []) + audit
            return lines
        header = (f"{'Consumer':<12}{'Op':<16}{'Shape':<18}"
                  f"{'Axes':<16}{'Impl':<14}{'Block':<8}{'Source':<12}"
                  f"{'Est(us)':<10}")
        lines = ["Collective plan:", header, "-" * len(header)]
        for sig in sorted(self.plan_records):
            r = self.plan_records[sig]
            lines.append(
                f"{r.get('consumer', '?'):<12}{r.get('op', '?'):<16}"
                f"{r.get('shape', '?'):<18}{r.get('axes', '?'):<16}"
                f"{r.get('impl', '?'):<14}{str(r.get('block') or '-'):<8}"
                f"{r.get('source', '?'):<12}"
                f"{str(r.get('est_us') if r.get('est_us') is not None else '-'):<10}"
                + (f" {r['program']}" if r.get("program") else ""))
        mem = self.memory_table_lines()
        if mem:
            lines += [""] + mem
        audit = self.analysis_table_lines()
        if audit:
            lines += [""] + audit
        return lines

    def monitor_events(self, step: int, prefix: str = "Train/Comms"):
        """``Monitor.write_events``-compatible events from the per-op totals
        — the bridge that gets ledger data into TensorBoard/CSV/W&B instead
        of only stdout. One event per (op, measure) at ``step``."""
        events = []
        for op_name, t in sorted(self.totals().items()):
            events.append((f"{prefix}/{op_name}/bytes", t["bytes"], step))
            events.append((f"{prefix}/{op_name}/wire_bytes",
                           t["wire_bytes"], step))
            events.append((f"{prefix}/{op_name}/total_latency_ms",
                           t["total_latency_ms"], step))
            events.append((f"{prefix}/{op_name}/count", t["count"], step))
        return events

    def totals(self) -> Dict[str, Dict[str, Any]]:
        """Aggregate per-op totals: op -> {count, bytes, wire_bytes,
        total_latency_ms} — logical bytes are count-weighted (one entry per
        issued collective), wire bytes are the accumulated on-wire totals."""
        out: Dict[str, Dict[str, Any]] = {}
        for op_name, sizes in self.comms_dict.items():
            count = byts = wire = 0
            lat = 0.0
            for size, rec in sizes.items():
                count += rec[0]
                byts += size * rec[0]
                lat += rec[1]
                wire += rec[3]
            out[op_name] = {"count": count, "bytes": byts, "wire_bytes": wire,
                            "total_latency_ms": lat * 1e3}
        return out

    def log_summary(self, world_size: int = 1, show_straggler: bool = False) -> Dict[str, Dict[str, Any]]:
        """Print the reference count/size/latency/bw table and RETURN the
        per-op totals dict (op -> {count, bytes, wire_bytes, ...}) so bench
        and the monitor can record the numbers without re-parsing stdout."""
        lines = []
        header = (f"{'Comm op':<28}{'Message size':<16}{'Count':<8}{'Total lat(ms)':<15}"
                  f"{'Avg lat(ms)':<13}{'algbw(GB/s)':<13}{'busbw(GB/s)':<13}{'wire':<10}")
        lines.append(header)
        lines.append("-" * len(header))
        for op_name, sizes in sorted(self.comms_dict.items()):
            for size, (count, total_lat, traced, wire) in sorted(sizes.items()):
                timed_count = count - traced
                avg = total_lat / timed_count if timed_count else 0.0
                algbw, busbw = calc_bw(op_name, size, avg, world_size)
                logical = size * count
                ratio = f"{logical / wire:.2f}x" if wire and wire < logical else "1x"
                note = f"(+{traced} traced)" if traced else ""
                lines.append(f"{op_name:<28}{get_msg_size(size):<16}{count:<8}"
                             f"{total_lat*1e3:<15.2f}{avg*1e3:<13.3f}{algbw:<13.2f}{busbw:<13.2f}"
                             f"{ratio:<10}{note}")
        plan = self.plan_table_lines()
        if plan:
            lines += [""] + plan
        print("\n".join(lines), flush=True)
        return self.totals()

    def hop_totals(self) -> Dict[str, int]:
        """Wire bytes per link class (``{"ici": .., "dcn": ..}``) — empty
        unless hop-aware collectives (multi-phase programs) ran."""
        return dict(self.hop_bytes)

    def hop_exposure(self) -> Dict[str, Dict[str, int]]:
        """Per link class: ``{"wire": total, "hidden": overlapped,
        "exposed": total - overlapped}`` — hidden bytes are the fused-phase
        hops that ride behind their bound matmul's tiles. The t3 bench's
        exposed-collective fraction is ``sum(exposed) / sum(wire)``."""
        out: Dict[str, Dict[str, int]] = {}
        for link, wire in self.hop_bytes.items():
            hidden = self.hop_hidden_bytes.get(link, 0)
            out[link] = {"wire": int(wire), "hidden": int(hidden),
                         "exposed": int(wire - hidden)}
        return out

    def log_hop_bytes(self, link: str, nbytes: int,
                      hidden: bool = False) -> None:
        """Attribute already-ledgered wire bytes to a link class — for
        program phases whose underlying primitive (the ppermute chunk ring)
        writes its own per-op ledger entry without hop awareness.
        ``hidden`` marks them compute-overlapped (see ``hop_exposure``)."""
        if not self.enabled:
            return
        self.hop_bytes[link] += int(nbytes)
        if hidden:
            self.hop_hidden_bytes[link] += int(nbytes)

    def reset(self):
        self.comms_dict.clear()
        self.hop_bytes.clear()
        self.hop_hidden_bytes.clear()


class timed_op:
    """Context manager timing an eager collective and appending to the ledger."""

    def __init__(self, ledger: CommsLogger, op_name: str, size_bytes: int):
        self.ledger = ledger
        self.op_name = op_name
        self.size_bytes = size_bytes

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.ledger.append(self.op_name, self.size_bytes, time.perf_counter() - self.t0)
        return False
