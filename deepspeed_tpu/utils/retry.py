"""Shared transient-failure retry: exponential backoff with decorrelated
jitter, a per-call deadline budget, and retryable-error classification.

Every non-collective transport in the tree (heartbeat beacons over the
object store, the on-disk plan cache, the snapshot manifest commit) used to
fail hard on the first transient error — one EAGAIN on a shared bucket and
a healthy host read as dead. This module is the one retry loop they all
share, so the policy (and its observability) lives in one place:

- **backoff** — decorrelated jitter (``sleep = min(cap, uniform(base,
  prev*3))``): concurrent retriers de-synchronize instead of hammering the
  store in lockstep;
- **deadline budget** — a call gives up when either ``max_attempts`` or
  ``deadline_s`` runs out, whichever comes first, and the final failure is
  a :class:`RetryError` (an ``OSError`` subclass, so existing I/O-failure
  handling degrades the same way it always did);
- **classification** — only ``retryable`` exception classes are retried,
  and ``non_retryable`` subclasses (``FileNotFoundError``, ``KeyError`` —
  an *absent* object is a fact, not a transient) pass straight through;
- **observability** — every retry is logged, counted into the telemetry
  registry as ``dstpu_retry_total{site=...}``, appended to a bounded
  in-process log that rides crash flight dumps (``retries`` in
  ``flightdump-<rank>.json`` — the doctor can then show "host X retried
  the bucket 14x before the dead verdict"), and forwarded to an optional
  monitor sink (``Resilience/retry/*`` events when a ResilienceManager is
  live).

Stdlib-only at import time; the telemetry registry is imported lazily so
standalone drill scripts can use the loop without the package.
"""

import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

try:
    from .logging import logger
except ImportError:  # loaded standalone (file-path import in drill scripts)
    import logging

    logger = logging.getLogger("deepspeed_tpu.retry")


class RetryError(OSError):
    """Retries exhausted (attempts or deadline). ``last`` carries the final
    underlying error; subclassing OSError keeps existing I/O-failure
    handling (plan-cache miss, beacon-absent) working unchanged."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(f"{site}: gave up after {attempts} attempt(s): "
                         f"{last!r}")
        self.site = site
        self.attempts = attempts
        self.last = last


@dataclass(frozen=True)
class RetryPolicy:
    """One transport's retry envelope. ``base_s``..``cap_s`` bound the
    decorrelated-jitter sleeps; ``deadline_s`` caps the whole call (None =
    attempts-only)."""
    max_attempts: int = 5
    base_s: float = 0.05
    cap_s: float = 2.0
    deadline_s: Optional[float] = 30.0
    retryable: Tuple[type, ...] = (OSError, ConnectionError, TimeoutError)
    non_retryable: Tuple[type, ...] = (FileNotFoundError, KeyError,
                                       IsADirectoryError)


DEFAULT_POLICY = RetryPolicy()

# bounded in-process retry log: rides flight dumps so the doctor can show
# the retry storm that preceded a dead-host verdict
_LOG_MAX = 256
_log: "deque" = deque(maxlen=_LOG_MAX)
_log_lock = threading.Lock()
# optional monitor sinks, keyed by the callable itself so several owners
# (e.g. a live engine's ResilienceManager AND an autotuner probe engine's)
# can coexist and each remove only its own: fn(site, attempt, error_repr,
# final) -> None. Registration happens on engine-init/finalizer threads
# while retriers iterate — lock-guarded like the retry log.
_monitor_sinks: Dict[int, Callable[[str, int, str, bool], None]] = {}
_sinks_lock = threading.Lock()


def add_retry_monitor(fn: Callable[[str, int, str, bool], None]) -> None:
    """Register a retry event sink — the ResilienceManager forwards these
    as ``Resilience/retry/*`` monitor events. Idempotent per callable
    OBJECT: sinks key by ``id(fn)``, so pass the SAME object to
    :func:`remove_retry_monitor` later (materialize a bound method once —
    ``obj.method`` builds a fresh object on every attribute access)."""
    with _sinks_lock:
        _monitor_sinks[id(fn)] = fn


def remove_retry_monitor(fn: Callable[[str, int, str, bool], None]) -> None:
    """Remove one owner's sink (the same object passed to
    :func:`add_retry_monitor`); other registered sinks keep receiving
    (closing a probe engine must not silence the live engine's events)."""
    with _sinks_lock:
        _monitor_sinks.pop(id(fn), None)


def retry_log_snapshot():
    """The bounded retry log as a list of dicts (newest last) — what the
    flight recorder folds into ``flightdump-<rank>.json``."""
    with _log_lock:
        return list(_log)


def clear_retry_log() -> None:
    with _log_lock:
        _log.clear()


def _note(site: str, attempt: int, err: BaseException, final: bool) -> None:
    entry = {"site": site, "attempt": attempt, "error": repr(err)[:200],
             "final": final, "wall_time": time.time()}
    with _log_lock:
        _log.append(entry)
    try:  # telemetry registry is optional (standalone loads, broken installs)
        from ..telemetry.registry import get_registry

        get_registry().counter(
            "dstpu_retry_total",
            "transient-transport retries by call site").inc(site=site)
    except Exception:
        pass
    with _sinks_lock:
        sinks = tuple(_monitor_sinks.values())
    for sink in sinks:
        try:
            sink(site, attempt, repr(err)[:200], final)
        except Exception:
            pass
    if final:
        logger.warning(f"retry[{site}]: giving up after {attempt} "
                       f"attempt(s): {err!r}")
    else:
        logger.warning(f"retry[{site}]: attempt {attempt} failed ({err!r}); "
                       f"backing off")


def retry_call(fn: Callable, *, site: str,
               policy: RetryPolicy = DEFAULT_POLICY,
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               clock: Callable[[], float] = time.monotonic):
    """Call ``fn()`` under ``policy``; returns its value or raises
    :class:`RetryError` once attempts/deadline run out. Non-retryable
    errors (absent object, programming errors) pass through untouched.
    ``sleep``/``rng``/``clock`` are injectable so tests run instantly and
    deterministically."""
    rng = rng or random
    t0 = clock()
    delay = policy.base_s
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except policy.retryable as e:
            if isinstance(e, policy.non_retryable):
                raise
            out_of_budget = (policy.deadline_s is not None
                             and clock() - t0 >= policy.deadline_s)
            if attempt >= policy.max_attempts or out_of_budget:
                _note(site, attempt, e, final=True)
                raise RetryError(site, attempt, e) from e
            _note(site, attempt, e, final=False)
            # decorrelated jitter: next sleep is uniform over [base, 3*prev],
            # capped — concurrent retriers drift apart instead of thundering
            delay = min(policy.cap_s, rng.uniform(policy.base_s, delay * 3.0))
            if policy.deadline_s is not None:
                delay = min(delay, max(0.0, policy.deadline_s
                                       - (clock() - t0)))
            sleep(delay)
