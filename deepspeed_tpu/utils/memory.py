"""Memory introspection (reference ``see_memory_usage``,
``runtime/utils.py:771`` + ``memory_breakdown`` engine knob).

Two views:
* :func:`see_memory_usage` — live device HBM stats (accelerator
  ``memory_stats``) + host RSS/available, logged rank-0.
* :func:`compiled_memory_analysis` — XLA's per-program accounting
  (argument/output/temp/generated-code bytes) for a jitted function, the
  TPU-native analogue of torch's allocator breakdown: under XLA the
  interesting number is what the COMPILED program reserves, not a runtime
  allocator's high-water mark.
"""

from typing import Any, Dict, Optional

from .logging import log_dist, logger


def _host_memory() -> Dict[str, float]:
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        out = {"host_max_rss_gb": rss_kb / 1024 / 1024}
    except Exception:  # pragma: no cover
        out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    out["host_available_gb"] = int(line.split()[1]) / 1024 / 1024
                    break
    except OSError:  # pragma: no cover
        pass
    return out


def memory_status() -> Dict[str, float]:
    """Device + host memory numbers (GB)."""
    from ..accelerator import get_accelerator

    acc = get_accelerator()
    stats = acc.memory_stats()
    gb = 1024 ** 3
    out = {
        "device_in_use_gb": stats.get("bytes_in_use", 0) / gb,
        "device_peak_gb": stats.get("peak_bytes_in_use", 0) / gb,
        "device_limit_gb": stats.get("bytes_limit", 0) / gb,
    }
    out.update(_host_memory())
    return out


def see_memory_usage(message: str, force: bool = False):
    """Reference ``see_memory_usage(message, force)``: rank-0 log of the
    current device/host memory picture. ``force=False`` is a no-op (the
    reference gates on its ``memory_breakdown`` config the same way)."""
    if not force:
        return
    s = memory_status()
    log_dist(
        f"{message} | MA {s['device_in_use_gb']:.2f} GB  "
        f"Max_MA {s['device_peak_gb']:.2f} GB  "
        f"Limit {s['device_limit_gb']:.2f} GB | "
        f"host max-RSS {s.get('host_max_rss_gb', 0):.2f} GB  "
        f"host-avail {s.get('host_available_gb', 0):.2f} GB")
    return s


def compiled_memory_analysis(jitted_fn, *args, **kwargs) -> Optional[Dict[str, Any]]:
    """XLA memory accounting for ``jitted_fn(*args)``: lowering + compile are
    cache hits when the function already ran with these shapes."""
    try:
        analysis = jitted_fn.lower(*args, **kwargs).compile().memory_analysis()
    except Exception as e:  # backend without memory analysis
        logger.debug(f"memory_analysis unavailable: {e}")
        return None
    if analysis is None:
        return None
    gb = 1024 ** 3
    fields = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")
    return {f.replace("_in_bytes", "_gb"): getattr(analysis, f, 0) / gb
            for f in fields}
