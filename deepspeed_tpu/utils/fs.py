"""Durable small-file writes shared by the checkpoint and resilience tiers.

A pointer file ('latest', a snapshot manifest) must never be observable
half-written: a reader that races a plain ``open(...).write`` — or a crash
mid-write — sees a torn file and the whole recovery chain dereferences
garbage. The POSIX recipe is write-to-temp + fsync + atomic ``os.replace``
into place; readers then see either the old content or the new, never a
prefix of the new.
"""

import json
import os
import tempfile
from typing import Any


def fsync_write_text(path: str, data: str) -> None:
    """Atomically replace ``path`` with ``data`` (temp + fsync + rename)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix="." + os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def fsync_write_json(path: str, obj: Any, **json_kw) -> None:
    fsync_write_text(path, json.dumps(obj, **json_kw))


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives a crash (best
    effort — some filesystems refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
