"""ZeRO parameter / gradient / optimizer-state access API.

TPU re-design of the reference ``deepspeed/utils/tensor_fragment.py``
(``safe_get_full_fp32_param`` etc., the surface RLHF/LoRA frameworks use to
read and write training state that ZeRO has partitioned). The reference
resolves flat-buffer fragment addresses per rank and allgathers them; here
params are a sharded pytree, so "full" is one ``jax.device_get`` of a
global array (orbax-style addressability) and "local" is one chip's shard.

Addressing: the reference passes the ``torch.nn.Parameter`` object; a JAX
pytree has no stable leaf identity, so leaves are addressed by **path** —
``"blocks.attn.wq"`` (dots or slashes), with integer components indexing
sequences. The engine argument is the ``DeepSpeedTPUEngine``.

Availability contract (mirrors the reference):

* params and optimizer state are always readable/writable;
* gradients exist only inside an imperative ``backward()`` accumulation
  window — the fused ``train_batch`` consumes its gradients inside one XLA
  program, so ``safe_get_full_grad`` returns ``None`` there (the reference
  likewise returns ``None`` + warns when no grad has been accumulated).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .logging import logger

__all__ = [
    "safe_get_full_fp32_param", "safe_set_full_fp32_param",
    "safe_get_full_grad", "safe_set_full_grad",
    "safe_get_full_optimizer_state", "safe_set_full_optimizer_state",
    "safe_get_local_fp32_param", "safe_get_local_grad",
    "safe_get_local_optimizer_state", "safe_set_local_fp32_param",
    "safe_set_local_grad", "safe_set_local_optimizer_state",
]


def _parts(path):
    if isinstance(path, (list, tuple)):
        return list(path)
    return [p for p in str(path).replace("/", ".").split(".") if p]


def _resolve(tree, path):
    node = tree
    for p in _parts(path):
        if hasattr(node, "_fields") and isinstance(p, str) and p in node._fields:
            node = getattr(node, p)  # NamedTuple by field name, like _replace
        elif isinstance(node, (list, tuple)):
            node = node[int(p)]
        elif isinstance(node, dict):
            if p not in node:
                raise KeyError(
                    f"path component {p!r} not found; available: "
                    f"{sorted(node)[:12]}")
            node = node[p]
        else:
            node = getattr(node, p)
    return node


def _replace(tree, path, value):
    """Functional leaf replacement along a dict/sequence/NamedTuple path —
    the write-side mirror of ``_resolve`` (which reads NamedTuples via
    getattr, so writes must address them by field name too)."""
    parts = _parts(path)
    if not parts:
        return value
    head, rest = parts[0], parts[1:]
    if isinstance(tree, dict):
        new = dict(tree)
        new[head] = _replace(tree[head], rest, value)
        return new
    if hasattr(tree, "_fields"):  # NamedTuple node
        return tree._replace(**{head: _replace(getattr(tree, head), rest,
                                               value)})
    if isinstance(tree, (list, tuple)):
        i = int(head)
        items = list(tree)
        items[i] = _replace(items[i], rest, value)
        return tuple(items) if isinstance(tree, tuple) else items
    raise TypeError(f"cannot descend into {type(tree).__name__} at {head!r}")


def _full_host_value(leaf) -> np.ndarray:
    # always a WRITABLE COPY: device_get can hand back read-only zero-copy
    # views, and get-then-mutate must never alias live training state
    if jax.process_count() > 1 and not getattr(leaf, "is_fully_addressable", True):
        from jax.experimental import multihost_utils

        return np.array(multihost_utils.process_allgather(leaf, tiled=True))
    return np.array(jax.device_get(leaf))


def _local_shard(leaf, device_index: int = 0) -> np.ndarray:
    """One chip's partition (reference 'local' = this rank's fragment;
    rank == chip on TPU, and one process drives several chips). Always a
    writable copy — same no-alias contract as ``_full_host_value``."""
    shards = getattr(leaf, "addressable_shards", None)
    if not shards:
        return np.array(leaf)
    return np.array(shards[device_index].data)


# -- params -----------------------------------------------------------------


def safe_get_full_fp32_param(engine, path) -> np.ndarray:
    """Full fp32 master value of a (possibly ZeRO-sharded) parameter
    (reference ``tensor_fragment.py:214``)."""
    if engine._host_adam is not None:  # masters live on host (ZeRO-Offload)
        # copy, never a live alias of the master (the device path copies too)
        return np.array(_resolve(engine._host_adam.master, path),
                        dtype=np.float32)
    return _full_host_value(_resolve(engine.state.params, path)).astype(
        np.float32)


def safe_set_full_fp32_param(engine, path, value) -> None:
    """Write a full fp32 master value back under the existing sharding
    (reference ``safe_set_full_fp32_param``). Under ZeRO-Offload both the
    host master and the device compute copy are updated."""
    old = _resolve(engine.state.params, path)
    value = jnp.asarray(value)
    if value.shape != old.shape:
        raise ValueError(f"shape mismatch at {path}: {value.shape} vs {old.shape}")
    if engine._host_adam is not None:
        master = _resolve(engine._host_adam.master, path)
        np.copyto(master, np.asarray(value, dtype=np.float32))
    new_leaf = jax.device_put(value.astype(old.dtype), old.sharding)
    engine.state = engine.state.replace(
        params=_replace(engine.state.params, path, new_leaf))
    # a forward() cached before this write holds grads/loss computed against
    # the OLD params — drop it (same staleness rule as engine.step)
    engine._compat_pending = None


def safe_get_local_fp32_param(engine, path, device_index: int = 0):
    if engine._host_adam is not None:
        return safe_get_full_fp32_param(engine, path)
    return _local_shard(_resolve(engine.state.params, path),
                        device_index).astype(np.float32)


def safe_set_local_fp32_param(engine, path, value, device_index: int = 0):
    """Per-chip shard writes don't exist as an efficient primitive under
    SPMD (a global array owns its layout); emulate by read-modify-write of
    the full value — correctness over speed, like the reference's
    narrow+copy under ZeRO-3."""
    full = safe_get_full_fp32_param(engine, path)
    leaf = _resolve(engine.state.params, path)
    shards = getattr(leaf, "addressable_shards", None)
    if not shards:
        return safe_set_full_fp32_param(engine, path, value)
    idx = shards[device_index].index
    full[idx] = np.asarray(value, dtype=np.float32)
    return safe_set_full_fp32_param(engine, path, full)


# -- gradients --------------------------------------------------------------


def _grad_denom(engine) -> float:
    """The raw compat accumulator holds loss-scale-multiplied, gas-summed
    grads (``engine.step`` divides by ``scale * gas`` before the optimizer);
    get/set translate so callers always see TRUE gradient magnitudes —
    the reference API contract."""
    scale = 1.0
    if engine.fp16:
        scale = float(np.asarray(engine.state.loss_scale.scale))
    return scale * engine.gas


def safe_get_full_grad(engine, path) -> Optional[np.ndarray]:
    """Accumulated gradient for a param in true (unscaled, gas-averaged)
    magnitude, or ``None`` outside an imperative ``backward()`` window
    (reference returns None + warns when the grad buffer does not exist)."""
    if engine._compat_acc is None:
        logger.warning(
            "safe_get_full_grad: no accumulated gradients — the fused "
            "train_batch consumes grads inside one XLA program; use the "
            "backward()/step() path to inspect them")
        return None
    raw = _full_host_value(_resolve(engine._compat_acc, path))
    return raw / _grad_denom(engine)


def safe_set_full_grad(engine, path, value) -> None:
    """Write a TRUE-magnitude gradient; it is re-scaled into the raw
    accumulator so ``step()`` consumes exactly ``value``."""
    if engine._compat_acc is None:
        raise RuntimeError(
            "safe_set_full_grad: no accumulated gradients to modify; call "
            "backward() first (the fused train_batch path has no persistent "
            "grad buffer)")
    old = _resolve(engine._compat_acc, path)
    value = jnp.asarray(value, dtype=old.dtype)
    if value.shape != old.shape:
        raise ValueError(f"shape mismatch at {path}: {value.shape} vs {old.shape}")
    new_leaf = jax.device_put(value * _grad_denom(engine), old.sharding)
    engine._compat_acc = _replace(engine._compat_acc, path, new_leaf)
    # a cached forward() would re-commit its pre-write accumulator on the
    # next backward(), overwriting this edit — invalidate it
    engine._compat_pending = None


def safe_get_local_grad(engine, path, device_index: int = 0):
    full = safe_get_full_grad(engine, path)
    if full is None:
        return None
    leaf = _resolve(engine._compat_acc, path)
    shards = getattr(leaf, "addressable_shards", None)
    return full[shards[device_index].index] if shards else full


def safe_set_local_grad(engine, path, value, device_index: int = 0):
    full = safe_get_full_grad(engine, path)
    if full is None:
        raise RuntimeError("safe_set_local_grad: no accumulated gradients")
    leaf = _resolve(engine._compat_acc, path)
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
        full = np.array(full)  # never mutate a possibly read-only view
        full[shards[device_index].index] = np.asarray(value)
    else:
        full = np.asarray(value)
    safe_set_full_grad(engine, path, full)


# -- optimizer state --------------------------------------------------------


def _find_optim_subtree(opt_state, key: str):
    """Locate the params-congruent moment tree named ``key`` (reference
    state keys: exp_avg / exp_avg_sq; our ScaleByAdamState uses the same
    names, optax chains/multi_transform may nest it)."""
    found = []

    def walk(node):
        if hasattr(node, "_fields"):  # NamedTuple state
            if key in node._fields:
                found.append(getattr(node, key))
            for f in node._fields:
                walk(getattr(node, f))
        elif isinstance(node, (list, tuple)):
            for item in node:
                walk(item)
        elif isinstance(node, dict):
            for item in node.values():
                walk(item)

    walk(opt_state)
    return found[0] if found else None


def safe_get_full_optimizer_state(engine, path, optim_state_key: str):
    """Full value of one optimizer-state tensor, e.g. ``exp_avg`` /
    ``exp_avg_sq`` (reference ``tensor_fragment.py:245``)."""
    if engine._host_adam is not None:
        tree = {"exp_avg": engine._host_adam.exp_avg,
                "exp_avg_sq": engine._host_adam.exp_avg_sq}.get(optim_state_key)
        if tree is None:
            raise ValueError(f"unknown optimizer state key {optim_state_key!r}")
        return np.array(_resolve(tree, path))  # copy, not a live alias
    sub = _find_optim_subtree(engine.state.opt_state, optim_state_key)
    if sub is None:
        raise ValueError(
            f"optimizer state has no {optim_state_key!r} tree (optimizer: "
            f"{engine.config.optimizer.type})")
    return _full_host_value(_resolve(sub, path))


def safe_get_local_optimizer_state(engine, path, optim_state_key: str,
                                   device_index: int = 0):
    if engine._host_adam is not None:
        return safe_get_full_optimizer_state(engine, path, optim_state_key)
    sub = _find_optim_subtree(engine.state.opt_state, optim_state_key)
    if sub is None:
        raise ValueError(f"no {optim_state_key!r} in optimizer state")
    return _local_shard(_resolve(sub, path), device_index)


def safe_set_full_optimizer_state(engine, path, value, optim_state_key: str):
    if engine._host_adam is not None:
        tree = {"exp_avg": engine._host_adam.exp_avg,
                "exp_avg_sq": engine._host_adam.exp_avg_sq}.get(optim_state_key)
        if tree is None:
            raise ValueError(f"unknown optimizer state key {optim_state_key!r}")
        dst = _resolve(tree, path)
        value = np.asarray(value, dtype=np.float32)
        if value.shape != dst.shape:  # copyto would silently broadcast
            raise ValueError(
                f"shape mismatch at {path}: {value.shape} vs {dst.shape}")
        np.copyto(dst, value)
        return
    sub = _find_optim_subtree(engine.state.opt_state, optim_state_key)
    if sub is None:
        raise ValueError(f"no {optim_state_key!r} in optimizer state")
    old = _resolve(sub, path)
    value = jnp.asarray(value, dtype=old.dtype)
    if value.shape != old.shape:
        raise ValueError(f"shape mismatch at {path}: {value.shape} vs {old.shape}")
    new_leaf = jax.device_put(value, old.sharding)
    done = []  # write ONLY the first match — the same subtree the getter reads

    def swap(node):
        if done:
            return node
        if hasattr(node, "_fields") and optim_state_key in node._fields:
            done.append(True)
            return node._replace(**{optim_state_key: _replace(
                getattr(node, optim_state_key), path, new_leaf)})
        if hasattr(node, "_fields"):
            return type(node)(*[swap(getattr(node, f)) for f in node._fields])
        if isinstance(node, tuple):
            return tuple(swap(x) for x in node)
        if isinstance(node, list):
            return [swap(x) for x in node]
        if isinstance(node, dict):
            return {k: swap(v) for k, v in node.items()}
        return node

    engine.state = engine.state.replace(opt_state=swap(engine.state.opt_state))


def safe_set_local_optimizer_state(engine, path, value, optim_state_key: str,
                                   device_index: int = 0):
    if engine._host_adam is None:
        sub = _find_optim_subtree(engine.state.opt_state, optim_state_key)
        if sub is None:
            raise ValueError(f"no {optim_state_key!r} in optimizer state")
        leaf = _resolve(sub, path)
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            # gather only on the sharded path — the full-value fetch is a
            # device(+cross-host) transfer the other branches don't need
            full = np.array(safe_get_full_optimizer_state(
                engine, path, optim_state_key))
            full[shards[device_index].index] = np.asarray(value)
            return safe_set_full_optimizer_state(engine, path, full,
                                                 optim_state_key)
    return safe_set_full_optimizer_state(engine, path, value, optim_state_key)
