"""Rank-aware logging.

TPU-native analogue of the reference's ``deepspeed/utils/logging.py`` (logger,
``log_dist``) — rank filtering here keys off ``jax.process_index()`` instead of
torch.distributed ranks.
"""

import logging
import os
import sys
from typing import Iterable, Optional

_LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    log = logging.getLogger(name)
    if not log.handlers:
        handler = logging.StreamHandler(stream=sys.stderr)
        handler.setFormatter(logging.Formatter(_LOG_FORMAT))
        log.addHandler(handler)
    log.setLevel(os.environ.get("DSTPU_LOG_LEVEL", level))
    log.propagate = False
    return log


logger = create_logger()


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process indices (default: rank 0)."""
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else [0]
    if my_rank in ranks or -1 in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        print(message, flush=True)
