"""Parallel-group accessors (reference ``deepspeed/utils/groups.py``).

The reference builds torch process groups per parallelism flavor
(data/model/expert/sequence) and hands them to collectives. Here a "group"
is a **named mesh-axis scope** of the live :class:`~deepspeed_tpu.parallel.
Topology`: the accessor returns the axis name(s) — exactly what
``deepspeed_tpu.comm`` collectives take as ``axis=`` — and the
world-size/rank accessors read the same topology. ``initialize(ep_size=…)``
re-carves the topology like the reference's expert-group setup.

Rank accessors are **host-level**: inside a traced collective, use
``comm.axis_index(axis)`` for the per-device index; a single host process
drives all its chips, so "my rank along axis X" is only meaningful
per-device under SPMD.
"""

from typing import Sequence, Tuple, Union

from ..parallel.topology import Topology, TopologySpec, get_topology, set_topology

Axis = Union[str, Tuple[str, ...]]


def initialize(ep_size: int = 1, mpu=None) -> None:
    """Reference ``groups.initialize``: carve the expert-parallel axis into
    the current topology — every other spec field and the topology's device
    set are preserved (a subset-device or explicit-dp topology must not be
    silently widened to all of ``jax.devices()``).

    ``mpu`` is accepted for signature parity only: the reference would build
    model-parallel groups from it, but here mesh-axis topology supersedes an
    external model-parallel unit — warn so the caller gets a signal instead
    of silently topology-derived groups."""
    import dataclasses

    if mpu is not None:
        from .logging import logger

        logger.warning(
            "groups.initialize: ignoring mpu=%r — named mesh-axis topology "
            "supersedes an external model-parallel unit on TPU; set tensor/"
            "sequence degrees via TopologySpec (parallel/topology.py) or the "
            "tensor_parallel/sequence_parallel_size config knobs", mpu)
    topo = get_topology()
    set_topology(Topology(dataclasses.replace(topo.spec, ep=ep_size),
                          devices=list(topo.mesh.devices.flat)))


def _get_data_parallel_group() -> Axis:
    return get_topology().dp_axes


def _get_model_parallel_group() -> Axis:
    return "tp"


def _get_expert_parallel_group(group_name: str = "ep") -> Axis:
    return "ep"


def _get_expert_data_parallel_group(group_name: str = "ep") -> Axis:
    # data-parallel *between* expert replicas: the dp axes minus ep
    return "dp_outer"


def _get_sequence_parallel_group() -> Axis:
    return "sp"


def _clone_world_group() -> Axis:
    return get_topology().all_axes


def _get_data_parallel_world_size() -> int:
    return get_topology().dp_size


def _get_model_parallel_world_size() -> int:
    return get_topology().tp_size


def _get_expert_parallel_world_size(group_name: str = "ep") -> int:
    return get_topology().ep_size


def _get_expert_data_parallel_world_size(group_name: str = "ep") -> int:
    return get_topology().dp_outer_size


def _get_sequence_parallel_world_size() -> int:
    return get_topology().sp_size


def _get_expert_parallel_ranks(world_size: int, mp_size: int, ep_size: int
                               ) -> Tuple[Sequence, Sequence]:
    """Rank layout math (reference ``groups.py:_get_expert_parallel_ranks``):
    expert groups stride over model-parallel blocks, expert-data groups
    stride over expert blocks. Pure arithmetic, kept for checkpoint tools
    that reason about reference rank files."""
    dp_size = world_size // mp_size
    if dp_size % ep_size:
        raise ValueError(f"dp world {dp_size} not divisible by ep {ep_size}")
    expert_parallel_groups = []
    expert_data_parallel_groups = []
    for mp_rank in range(mp_size):
        dp_ranks = list(range(mp_rank, world_size, mp_size))
        for i in range(0, dp_size, ep_size):
            expert_parallel_groups.append(dp_ranks[i:i + ep_size])
        for i in range(ep_size):
            expert_data_parallel_groups.append(dp_ranks[i::ep_size])
    return expert_parallel_groups, expert_data_parallel_groups
