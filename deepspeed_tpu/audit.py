"""``python -m deepspeed_tpu.audit`` — static pre-flight audit of a step.

Point it at a step via ``--entry module:callable`` (the callable returns
what to audit, see below), or run the built-in ``--demo`` pair that proves
the collective-reconciliation contract end to end: ``--demo misaligned``
shards a weight on the wrong dim and the auditor names the all-gather XLA
silently inserted to fix it up; ``--demo clean`` is the aligned twin and
reports zero unplanned collectives.  Exit code ``2`` when findings at or
above ``--fail-on`` exist (the doctor's convention — CI-assertable),
``0`` clean, ``1`` usage error.

An ``--entry`` callable returns either a ``jax.stages.Traced`` /
``Lowered``, or a dict with keys ``fn`` (callable), ``args`` (tuple), and
optionally ``kwargs`` / ``in_shardings`` / ``out_shardings`` /
``donate_argnums`` / ``axis_sizes`` / ``label``.

Nothing executes on a device: trace + lower + host compile only.
See ``docs/static_analysis.md``.
"""

import argparse
import importlib
import json
import os
import sys


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.audit",
        description="Static pre-flight audit: unplanned collectives, "
                    "precision leaks, donation misses, host-sync hazards "
                    "— before the first step runs.")
    ap.add_argument("--entry", default=None, metavar="MODULE:CALLABLE",
                    help="import MODULE and call CALLABLE() to get the "
                         "step to audit")
    ap.add_argument("--demo", choices=("clean", "misaligned"), default=None,
                    help="built-in 2x4-mesh demo: 'misaligned' shards a "
                         "weight on the wrong dim (the auditor names the "
                         "implicit all-gather, exit 2); 'clean' is the "
                         "aligned twin (exit 0)")
    ap.add_argument("--fail-on", default="error",
                    choices=("info", "warning", "error"),
                    help="exit 2 when findings at/above this severity "
                         "exist (default: error)")
    ap.add_argument("--strict", action="store_true",
                    help="unmatched reduction collectives become warnings "
                         "instead of info")
    ap.add_argument("--allow", action="append", default=[],
                    metavar="REGEX",
                    help="collective allow-list regex (vs HLO metadata "
                         "op_name/source); repeatable")
    ap.add_argument("--out", default=None,
                    help="write audit-report.json here")
    ap.add_argument("--json", action="store_true",
                    help="print the report JSON instead of the rendering")
    return ap.parse_args(argv)


def _build_demo(which: str):
    """The acceptance-criterion pair: one matmul chain, sharded right and
    sharded wrong.  Needs >= 8 devices (main() forces the virtual CPU mesh
    before jax loads when real devices are absent)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit(f"audit --demo needs 8 devices, have {len(devs)}")
    mesh = Mesh(np.array(devs[:8]).reshape(2, 4), ("dp", "tp"))
    axis_sizes = {"dp": 2, "tp": 4}
    x = jnp.ones((32, 1024), jnp.bfloat16)
    w1 = jnp.ones((1024, 4096), jnp.bfloat16)  # 8 MiB: error-grade payload
    w2 = jnp.ones((4096, 1024), jnp.bfloat16)

    def step(x, w1, w2):
        h = jnp.tanh(x @ w1)
        y = h @ w2
        return jnp.mean(y.astype(jnp.float32) ** 2)

    def sh(*spec):
        return NamedSharding(mesh, P(*spec))  # spec-ok: demo harness sharding for a synthetic program

    if which == "clean":
        # Megatron pairing: col-parallel w1, row-parallel w2 — the only
        # collective is the row psum + the dp mean, both reductions
        in_sh = (sh("dp", None), sh(None, "tp"), sh("tp", None))
    else:
        # w1 sharded on dim 0 (the CONTRACTION dim of x @ w1) instead of
        # dim 1: GSPMD must all-gather the full weight on every rank —
        # the classic AutoTP-rule-gone-wrong shape
        in_sh = (sh("dp", None), sh("tp", None), sh("tp", None))
    return {"fn": step, "args": (x, w1, w2), "in_shardings": in_sh,
            "out_shardings": sh(), "axis_sizes": axis_sizes,
            "label": f"demo-{which}"}


def _load_entry(spec: str):
    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"--entry wants MODULE:CALLABLE, got {spec!r}")
    sys.path.insert(0, os.getcwd())
    mod = importlib.import_module(mod_name)
    return getattr(mod, attr)()


def main(argv=None) -> int:
    args = _parse_args(argv)
    if (args.entry is None) == (args.demo is None):
        print("audit: pass exactly one of --entry or --demo",
              file=sys.stderr)
        return 1

    import jax

    if (args.demo and len(jax.devices()) < 8
            and jax.default_backend() == "cpu"
            and not os.environ.get("_DSTPU_AUDIT_REEXEC")):
        # the demo needs a mesh, and the XLA flag must be set before jax
        # initializes — which already happened when the package imported.
        # Re-exec once with 8 virtual CPU devices (host platform only;
        # never shrinks a real accelerator).
        env = dict(os.environ,
                   _DSTPU_AUDIT_REEXEC="1",
                   XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                              + " --xla_force_host_platform_device_count=8"))
        os.execve(sys.executable,
                  [sys.executable, "-m", "deepspeed_tpu.audit"]
                  + (argv if argv is not None else sys.argv[1:]), env)

    from .analysis import AuditOptions, AuditReport, audit_step

    opts = AuditOptions(strict=args.strict,
                        collective_allowlist=tuple(args.allow))
    if args.demo:
        spec = _build_demo(args.demo)
    else:
        spec = _load_entry(args.entry)

    if isinstance(spec, dict):
        report = audit_step(
            spec["fn"], *spec.get("args", ()),
            label=spec.get("label", "step"), options=opts,
            axis_sizes=spec.get("axis_sizes"),
            in_shardings=spec.get("in_shardings"),
            out_shardings=spec.get("out_shardings"),
            donate_argnums=spec.get("donate_argnums", ()),
            **spec.get("kwargs", {}))
    elif isinstance(spec, AuditReport):
        report = spec  # an entry may audit itself and hand back the report
    else:
        report = audit_step(spec, label="step", options=opts)

    if args.out:
        report.write(args.out)
        print(f"audit: report written to {args.out}", file=sys.stderr)
    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.render())
    return report.exit_code(args.fail_on)


if __name__ == "__main__":
    sys.exit(main())
