from .cross_entropy import (sharded_lm_loss, vocab_parallel_cross_entropy,
                            vocab_sequence_parallel_cross_entropy)
from .layer import ulysses_attention
from .ring import ring_attention, ring_attention_local

__all__ = ["ulysses_attention", "ring_attention", "ring_attention_local",
           "vocab_parallel_cross_entropy", "vocab_sequence_parallel_cross_entropy",
           "sharded_lm_loss"]
