from .layer import ulysses_attention
from .ring import ring_attention, ring_attention_local

__all__ = ["ulysses_attention", "ring_attention", "ring_attention_local"]
