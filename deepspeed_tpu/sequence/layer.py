"""Ulysses sequence parallelism.

Reference: ``DistributedAttention`` (``deepspeed/sequence/layer.py:271``) and
``single_all_to_all:153`` — scatter heads / gather sequence with an all-to-all
before any local attention, reverse after. On TPU the all-to-all is a native
ICI collective (``lax.all_to_all`` over the ``sp`` mesh axis inside
``shard_map``); comm volume stays O(N/P) per the Ulysses design.

GQA/uneven heads (reference ``uneven_heads_all2all:43``): the reference moves
kv tensors with an uneven-split ``all_to_all_single`` so each rank ends with
its own (possibly 0-or-1 extra) kv heads. Uneven per-rank shapes are hostile
to XLA's static SPMD model, so the TPU design is different but moves the same
bytes: when ``hk < sp`` (the GQA regime Ulysses targets) the kv exchange is a
two-phase subgroup collective —

  1. ``all_to_all`` within the ``hk`` rank-subgroups that share a residue
     ``r % (sp/hk)``: splits the kv-head axis (one head per subgroup member),
     concatenates partial sequence.  Bytes/rank: ``S*hk*D/sp``.
  2. ``all_gather`` within the ``sp/hk`` rank-subgroups that share a kv head:
     assembles the full sequence for that head.  Bytes/rank: ``~S*D``.

Total ``~S*D(1 + hk/sp)`` per rank vs ``S*h*D/sp`` for replicate-then-a2a — a
``~h/hk`` reduction, matching the reference's uneven-head saving. Head counts
not divisible by sp are padded up to alignment (TPU-idiomatic: pad, don't go
ragged) and sliced back after the reverse exchange.
"""

from typing import Callable

import jax
import jax.numpy as jnp

from ..parallel.topology import SP_AXIS, TP_AXIS, get_topology
from ..sharding import sites


def _a2a_quantized(x, sp: int, split_dim: int) -> bool:
    """Whether this Ulysses exchange rides the int8 all-to-all. The
    ``compressed_collectives`` knob wins when explicitly configured;
    otherwise the collective planner (``comm/planner``, mode
    static|measure) decides per site — off keeps the exact a2a."""
    from ..comm.compressed import compression_mode

    if x.shape[split_dim] % sp != 0:
        return False  # ragged split: always the exact collective
    if compression_mode() != "none":  # raw knob set (incl. site toggles)
        return compression_mode("ulysses") != "none"
    from ..comm.planner import planner_active, resolve_site

    if not planner_active():
        return False
    d = resolve_site(op="all_to_all", shape=x.shape, dtype=x.dtype,
                     axes=(SP_AXIS,), consumer="ulysses")
    return d.impl in ("int8", "int8_sr")


def _all_to_all_heads_to_seq(x, sp: int):
    """[B, S/sp, H, D] -> [B, S, H/sp, D] over the sp axis. With the
    ``compressed_collectives`` Ulysses site on (or the comm planner
    choosing int8 for this site), the payload rides int8 + one-lane scales
    (``comm/compressed.py``; backward stays the exact transposed
    exchange); ragged head counts fall back to the exact a2a."""
    from ..comm.compressed import quantized_all_to_all

    if _a2a_quantized(x, sp, split_dim=2):
        return quantized_all_to_all(x, SP_AXIS, split_dim=2, concat_dim=1)
    return jax.lax.all_to_all(x, SP_AXIS, split_axis=2, concat_axis=1, tiled=True)


def _all_to_all_seq_to_heads(x, sp: int):
    """[B, S, H/sp, D] -> [B, S/sp, H, D] (reverse exchange; same
    compression/planner gate as :func:`_all_to_all_heads_to_seq`)."""
    from ..comm.compressed import quantized_all_to_all

    if _a2a_quantized(x, sp, split_dim=1):
        return quantized_all_to_all(x, SP_AXIS, split_dim=1, concat_dim=2)
    return jax.lax.all_to_all(x, SP_AXIS, split_axis=1, concat_axis=2, tiled=True)


def _uneven_kv_exchange(x, sp: int, hk: int):
    """GQA kv exchange for ``hk < sp``: [B, S/sp, hk, D] -> [B, S, 1, D].

    Rank ``r`` (over the sp axis) ends holding kv head ``r // (sp/hk)`` over
    the *full* sequence — exactly the head its post-exchange q block attends
    to. Two subgroup collectives (see module docstring); both ride ICI.
    Requires ``sp % hk == 0`` (callers pad hk up to a divisor of sp first).
    """
    rep = sp // hk
    b, s_loc, _, d = x.shape
    # Phase 1: a2a among ranks {kvg*rep + j : kvg} for each residue j — one kv
    # head per member, partial sequence (hk chunks of the global S/sp grid).
    g1 = [[kvg * rep + j for kvg in range(hk)] for j in range(rep)]
    x = jax.lax.all_to_all(x, SP_AXIS, split_axis=2, concat_axis=1, tiled=True,
                           axis_index_groups=g1)  # [B, S_loc*hk, 1, D]
    # Phase 2: gather the remaining sequence chunks from the ranks that share
    # this kv head (residues j = 0..rep-1).
    g2 = [[kvg * rep + j for j in range(rep)] for kvg in range(hk)]
    x = jax.lax.all_gather(x, SP_AXIS, axis=1, tiled=True,
                           axis_index_groups=g2)  # [B, S_loc*hk*rep, 1, D]
    # Gathered chunk order is (j, kvg)-major; global chunk c = kvg*rep + j is
    # kvg-major — a static transpose restores sequence order.
    x = x.reshape(b, rep, hk, s_loc, 1, d)
    x = jnp.transpose(x, (0, 2, 1, 3, 4, 5))
    return x.reshape(b, rep * hk * s_loc, 1, d)


def _kv_head_map(h_padded: int, hk: int, group: int):
    """Static q-head -> kv-head index map. ``group`` is the TRUE GQA ratio
    (unpadded h // hk) — padded q heads clamp to the last kv head (their
    output is sliced away)."""
    return jnp.asarray([min(j // group, hk - 1) for j in range(h_padded)],
                       dtype=jnp.int32)


def ulysses_attention(local_attn: Callable, q, k, v):
    """Run ``local_attn(q, k, v, positions)`` under Ulysses SP.

    Inputs are global ``[B, S, H, D]`` arrays whose S dim is sharded over the
    ``sp`` mesh axis by the engine's batch spec. Inside the shard_map each rank
    holds ``S/sp`` of the sequence with all heads; after the exchange it holds
    the full sequence with ``H/sp`` heads — any local attention (including the
    Pallas flash kernel) then works unchanged, with global positions.

    KV routing per (local) head counts, chosen inside the body where shapes
    are per-shard (so TP composition sees tp-local head counts):
      * ``hk % sp == 0``  — even all-to-all, the reference's fast path.
      * ``sp % hk == 0``  — uneven-head subgroup exchange (module docstring):
        each rank receives exactly the one kv head its q block attends to,
        cutting kv bytes ~``h/hk``× vs replication.
      * otherwise        — explicit-index replication fallback (correct for
        any h/hk, costs the replicated bytes; also used when ``h % sp != 0``
        forces q-head padding, which breaks group alignment).
    """
    topo = get_topology()
    sp = topo.sp_size
    if sp == 1:
        return local_attn(q, k, v, None)

    h, hk = q.shape[2], k.shape[2]
    mesh = topo.mesh
    dp = topo.dp_axes
    # Compose with TP: heads arrive column-parallel over 'tp'; keep them
    # sharded through the exchange so no tp all-gather is forced. q and kv
    # shard independently — MQA/low-kv GQA keeps q over tp even when the kv
    # head count can't split (kv then routes via the tp-offset-aware map).
    tp = topo.tp_size
    q_axis = "tp" if (tp > 1 and h % (sp * tp) == 0) else None
    kv_axis = "tp" if (q_axis is not None and hk % tp == 0) else None
    q_spec = sites.ulysses_act(dp, SP_AXIS, q_axis)
    kv_spec = sites.ulysses_act(dp, SP_AXIS, kv_axis)
    h_pad = h if q_axis else -(-h // sp) * sp
    if h_pad != h:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, h_pad - h), (0, 0)))
    g_true = max(1, h // hk)  # TRUE GQA ratio (padding would skew it)

    def body(q_, k_, v_):
        hl, hkl = q_.shape[2], k_.shape[2]  # tp-local head counts
        qg = _all_to_all_heads_to_seq(q_, sp)
        if q_axis is not None and kv_axis is None and tp > 1:
            # q is tp-sharded, kv is not: this shard's q block starts at a
            # tp-dependent global head offset, so the kv head each local q
            # head needs is a traced index — gather it, then even a2a.
            tp_off = jax.lax.axis_index(TP_AXIS) * hl
            idx = jnp.minimum((tp_off + jnp.arange(hl)) // g_true, hkl - 1)
            _ledger_note("ulysses_kv_replicated", k_, sp, hkl, rep=hl)
            kg = _all_to_all_heads_to_seq(jnp.take(k_, idx, axis=2), sp)
            vg = _all_to_all_heads_to_seq(jnp.take(v_, idx, axis=2), sp)
        elif hkl % sp == 0:
            kg = _all_to_all_heads_to_seq(k_, sp)
            vg = _all_to_all_heads_to_seq(v_, sp)
        elif sp % hkl == 0 and h_pad == h and hl % sp == 0:
            _ledger_note("ulysses_kv_uneven", k_, sp, hkl)
            kg = _uneven_kv_exchange(k_, sp, hkl)
            vg = _uneven_kv_exchange(v_, sp, hkl)
        else:
            # Replication fallback: gather each q head's kv explicitly so any
            # h/hk ratio (incl. padded q heads) stays correct, then even a2a.
            idx = _kv_head_map(hl, hkl, g_true)  # local ratio == global ratio
            _ledger_note("ulysses_kv_replicated", k_, sp, hkl, rep=hl)
            kg = _all_to_all_heads_to_seq(jnp.take(k_, idx, axis=2), sp)
            vg = _all_to_all_heads_to_seq(jnp.take(v_, idx, axis=2), sp)
        out = local_attn(qg, kg, vg, None)  # full seq -> global positions
        return _all_to_all_seq_to_heads(out, sp)

    from ..utils.shard_map_compat import shard_map_nocheck

    out = shard_map_nocheck(body, mesh, in_specs=(q_spec, kv_spec, kv_spec),
                            out_specs=q_spec)(q, k, v)
    return out[:, :, :h, :] if h_pad != h else out


def ulysses_matmul_attention(local_attn, x, q_params, k_params, v_params,
                             o_params, *, dtype=None):
    """Ulysses with the projections fused into the sp exchange
    (``ops/collective_matmul.py`` ring primitives, T3-style).

    Instead of project-then-all-to-all, the qkv projections run as one ring
    ``all_gather_matmul`` over ``sp`` — each rank gathers the sequence while
    computing only its own head block — and the output projection runs as
    ``matmul_reduce_scatter``, whose reduction ring re-scatters the sequence.
    This replaces all four all-to-alls AND hides the remaining comm behind
    the projection matmuls; bytes/rank stay O(S*D) like the a2a path.

    ``x``: ``[B, S, D]`` with S sharded over sp (the engine batch layout);
    ``*_params`` are the flax DenseGeneral param dicts (``kernel``
    ``[D, H, Dh]`` for qkv / ``[H, Dh, D]`` for o, optional ``bias``).
    Caller guarantees ``h % sp == 0``, ``hk % sp == 0``, ``S % sp == 0`` and
    ``tp == 1`` (``ulysses_attention`` covers everything else). Returns the
    projected attention output ``[B, S, D]``.
    """
    from ..ops.collective_matmul import (fused_qkv_all_gather_matmul,
                                         matmul_reduce_scatter)
    from ..utils.shard_map_compat import shard_map_nocheck

    topo = get_topology()
    dp = topo.dp_axes
    dt = dtype or x.dtype
    wq, wk, wv = (p["kernel"].astype(dt)
                  for p in (q_params, k_params, v_params))
    wo = o_params["kernel"].astype(dt)
    dh = wq.shape[2]
    w_spec = sites.col_kernel3(SP_AXIS)
    args = [x.astype(dt), wq, wk, wv, wo]
    specs = [sites.seq_sharded_act(dp, SP_AXIS), w_spec, w_spec, w_spec,
             sites.row_kernel3(SP_AXIS)]
    if "bias" in q_params:
        args += [p["bias"].astype(dt) for p in (q_params, k_params, v_params)]
        specs += [sites.col_bias2(SP_AXIS)] * 3

    def body(x_, wq_, wk_, wv_, wo_, *bs):
        q_, k_, v_ = fused_qkv_all_gather_matmul(x_, wq_, wk_, wv_, bs, dh,
                                                 SP_AXIS)
        out = local_attn(q_, k_, v_, None)  # full seq, this rank's heads
        b_, s_, hl = out.shape[:3]
        return matmul_reduce_scatter(out.reshape(b_, s_, hl * dh),
                                     wo_.reshape(hl * dh, -1), SP_AXIS)

    out = shard_map_nocheck(body, topo.mesh, tuple(specs),
                            sites.seq_sharded_act(dp, SP_AXIS))(*args)
    if "bias" in o_params:
        out = out + o_params["bias"].astype(dt)
    return out


def _ledger_note(op: str, k_local, sp: int, hk_local: int, rep: int = 1):
    """Record kv-exchange bytes in the comms ledger at trace time, so the
    uneven-head saving is observable (uneven path: ~S*D*(1+hk/sp)/rank vs
    replicated: S*rep*D/sp with rep up to h)."""
    try:
        from ..comm.comm import get_comms_logger
    except Exception:  # pragma: no cover
        return
    b, s_loc, _, d = k_local.shape
    itemsize = jnp.dtype(k_local.dtype).itemsize
    if op == "ulysses_kv_uneven":
        nbytes = b * s_loc * hk_local * d * itemsize  # phase 1 send
        nbytes += b * s_loc * hk_local * d * itemsize * max(0, sp // hk_local - 1)  # phase 2
    else:
        nbytes = b * s_loc * rep * d * itemsize  # replicated heads through the a2a
    get_comms_logger().append(op, 2 * nbytes, traced=True)  # k and v

