"""Ulysses sequence parallelism.

Reference: ``DistributedAttention`` (``deepspeed/sequence/layer.py:271``) and
``single_all_to_all:153`` — scatter heads / gather sequence with an all-to-all
before any local attention, reverse after. On TPU the all-to-all is a native
ICI collective (``lax.all_to_all`` over the ``sp`` mesh axis inside
``shard_map``); comm volume stays O(N/P) per the Ulysses design.

GQA/uneven heads (reference ``uneven_heads_all2all:43``): when kv heads don't
divide the sp degree they are replicated up to the q-head count before the
exchange.
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.topology import SP_AXIS, get_topology


def _all_to_all_heads_to_seq(x, sp: int):
    """[B, S/sp, H, D] -> [B, S, H/sp, D] over the sp axis."""
    return jax.lax.all_to_all(x, SP_AXIS, split_axis=2, concat_axis=1, tiled=True)


def _all_to_all_seq_to_heads(x, sp: int):
    """[B, S, H/sp, D] -> [B, S/sp, H, D]."""
    return jax.lax.all_to_all(x, SP_AXIS, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(local_attn: Callable, q, k, v):
    """Run ``local_attn(q, k, v, positions)`` under Ulysses SP.

    Inputs are global ``[B, S, H, D]`` arrays whose S dim is sharded over the
    ``sp`` mesh axis by the engine's batch spec. Inside the shard_map each rank
    holds ``S/sp`` of the sequence with all heads; after the exchange it holds
    the full sequence with ``H/sp`` heads — any local attention (including the
    Pallas flash kernel) then works unchanged, with global positions.
    """
    topo = get_topology()
    sp = topo.sp_size
    if sp == 1:
        return local_attn(q, k, v, None)

    h, hk = q.shape[2], k.shape[2]
    if hk % sp != 0:  # GQA uneven heads: replicate kv up to q heads
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if h % sp != 0:
        raise ValueError(f"num_heads={h} must be divisible by sp={sp}")

    mesh = topo.mesh
    dp = topo.dp_axes
    # Compose with TP: heads arrive column-parallel over 'tp'; keep them
    # sharded through the exchange so no tp all-gather is forced.
    tp = topo.tp_size
    heads_axis = "tp" if (tp > 1 and h % (sp * tp) == 0 and k.shape[2] % (sp * tp) == 0) else None
    io_spec = P(dp, SP_AXIS, heads_axis, None)

    def body(q_, k_, v_):
        qg = _all_to_all_heads_to_seq(q_, sp)
        kg = _all_to_all_heads_to_seq(k_, sp)
        vg = _all_to_all_heads_to_seq(v_, sp)
        out = local_attn(qg, kg, vg, None)  # full seq -> global positions
        return _all_to_all_seq_to_heads(out, sp)

    return jax.shard_map(body, mesh=mesh, in_specs=(io_spec, io_spec, io_spec),
                         out_specs=io_spec, check_vma=False)(q, k, v)
