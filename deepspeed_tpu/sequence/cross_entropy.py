"""Vocab-parallel (and sequence-parallel) cross entropy.

Capability parity with ``deepspeed/sequence/cross_entropy.py:1-60``
(``_VocabSequenceParallelCrossEntropy``): compute the LM loss against a
*vocab-sharded* logits tensor without all-gathering the logits. At 32k-256k
vocab the full-vocab logits are the dominant activation at long sequence;
gathering them over tp defeats both TP and Ulysses.

TPU-native design: instead of a torch ``autograd.Function`` with a hand-written
backward, the loss is an ordinary differentiable composition of XLA collectives
inside ``shard_map`` —

  * ``pmax`` over the vocab axis for the stabilising max (stop-gradient: it
    only recentres the exponentials),
  * ``psum`` of the local sum-exp for the global partition function,
  * ``psum`` of the masked target-logit lookup (each target id lives in exactly
    one vocab shard).

JAX transposes ``psum``/``shard_map`` correctly, so ``jax.grad`` produces the
Megatron-style ``softmax - onehot`` backward with the logits *still sharded* —
no custom VJP needed, and XLA fuses the whole thing into the lm-head matmul
epilogue.

The reference's "sequence parallel" flavour additionally all-gathers the
per-token loss along sp; here the loss is returned as a global array whose sp
sharding the caller's reduction consumes directly — the mean is a psum, the
gather never materialises.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.topology import SP_AXIS, TP_AXIS, get_topology

__all__ = [
    "vocab_parallel_cross_entropy",
    "vocab_sequence_parallel_cross_entropy",
    "sharded_lm_loss",
]


def vocab_parallel_cross_entropy(local_logits, targets, *, axis_name: str = TP_AXIS,
                                 z_loss: float = 0.0):
    """Per-token NLL against vocab-sharded logits. For use inside ``shard_map``.

    Args:
      local_logits: ``[..., V/P]`` — this rank's contiguous vocab shard
        (shard ``i`` covers ids ``[i*V/P, (i+1)*V/P)``).
      targets: ``[...]`` int32 global token ids (same leading shape).
      axis_name: mesh axis the vocab is sharded over.
      z_loss: PaLM-style ``z_loss * log(Z)^2`` regulariser coefficient.

    Returns per-token loss ``[...]`` in float32, identical on every rank of
    ``axis_name`` (it is a psum reduction), differentiable w.r.t. local_logits.
    """
    local_logits = local_logits.astype(jnp.float32)
    vloc = local_logits.shape[-1]
    offset = jax.lax.axis_index(axis_name) * vloc

    # Stabilising max: stop-gradient — it cancels in logZ - target_logit.
    lmax = jax.lax.pmax(
        jax.lax.stop_gradient(jnp.max(local_logits, axis=-1)), axis_name)
    sumexp = jax.lax.psum(
        jnp.sum(jnp.exp(local_logits - lmax[..., None]), axis=-1), axis_name)
    logz = jnp.log(sumexp) + lmax

    t = targets - offset
    in_shard = (t >= 0) & (t < vloc)
    t_clip = jnp.clip(t, 0, vloc - 1)
    tgt = jnp.take_along_axis(local_logits, t_clip[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(jnp.where(in_shard, tgt, jnp.float32(0.0)), axis_name)

    nll = logz - tgt
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logz)
    return nll


def vocab_sequence_parallel_cross_entropy(logits, targets, *, z_loss: float = 0.0,
                                          topo=None):
    """Global-array entry point: ``[B, S, V]`` logits vocab-sharded over tp
    (and batch/seq sharded over dp/sp) -> per-token loss ``[B, S]``.

    Matches ``vocab_sequence_parallel_cross_entropy``
    (reference ``sequence/cross_entropy.py:59``) except the returned loss stays
    a (dp, sp)-sharded global array instead of being explicitly all-gathered —
    under jit the two are the same value.
    """
    topo = topo or get_topology()
    if topo.tp_size == 1:
        logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logits.astype(jnp.float32),
                                  targets[..., None], axis=-1)[..., 0]
        nll = logz - tgt
        return nll + z_loss * jnp.square(logz) if z_loss > 0 else nll

    dp = topo.dp_axes
    lg_spec = P(dp, SP_AXIS, TP_AXIS)
    tg_spec = P(dp, SP_AXIS)

    def body(lg, tg):
        return vocab_parallel_cross_entropy(lg, tg, axis_name=TP_AXIS,
                                            z_loss=z_loss)

    from ..utils.shard_map_compat import shard_map_nocheck

    return shard_map_nocheck(body, topo.mesh,
                             in_specs=(lg_spec, tg_spec),
                             out_specs=tg_spec)(logits, targets)


def sharded_lm_loss(hidden, head_kernel, tokens, *, loss_mask=None,
                    z_loss: float = 0.0, head_bias=None, topo=None,
                    logit_dtype=jnp.float32):
    """Fused vocab-sharded head matmul + cross entropy, next-token shifted.

    ``hidden`` is ``[B, S, E]`` (sp-sharded on S), ``head_kernel`` is
    ``[E, V]`` column-sharded over tp. The ``[B, S, V/tp]`` local logits exist
    only inside the shard_map body, fused by XLA with the reduction — the
    full-vocab activation is never resident. This is the composition the
    reference reaches with Megatron's parallel lm-head + its
    ``_VocabSequenceParallelCrossEntropy``.
    """
    topo = topo or get_topology()
    if topo.tp_size != 1:
        if head_kernel.shape[-1] % topo.tp_size != 0:
            raise ValueError(
                f"vocab_parallel_loss needs vocab_size ({head_kernel.shape[-1]}) "
                f"divisible by tp ({topo.tp_size}); pad the vocab up to a "
                "multiple of tp (Megatron pads for the same reason)")
        # Keep S full-length (divisible by sp): shift targets with a dummy
        # final position and fold the shift into the mask instead of slicing.
        targets_full = jnp.concatenate([tokens[:, 1:], tokens[:, -1:]], axis=1)
        w = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
        if loss_mask is not None:
            lm = loss_mask.astype(jnp.float32)
            w = w * jnp.concatenate([lm[:, 1:], jnp.zeros_like(lm[:, -1:])], axis=1)
        nll = _vocab_sharded_head_nll(hidden, head_kernel, targets_full,
                                      head_bias=head_bias, z_loss=z_loss,
                                      topo=topo, logit_dtype=logit_dtype)
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)
    from ..models.transformer import causal_lm_loss

    logits = hidden.astype(logit_dtype) @ head_kernel.astype(logit_dtype)
    if head_bias is not None:
        logits = logits + head_bias.astype(logit_dtype)
    return causal_lm_loss(logits, tokens, loss_mask=loss_mask, z_loss=z_loss)


def _vocab_sharded_head_nll(hidden, head_kernel, targets, *, head_bias,
                            z_loss, topo, logit_dtype):
    """shard_map body: local head matmul fused with the sharded CE."""
    dp = topo.dp_axes
    h_spec = P(dp, SP_AXIS, None)
    k_spec = P(None, TP_AXIS)
    tg_spec = P(dp, SP_AXIS)

    def body(h, k, b, tg):
        lg = h.astype(logit_dtype) @ k.astype(logit_dtype)
        if b is not None:
            lg = lg + b.astype(logit_dtype)
        return vocab_parallel_cross_entropy(lg, tg, axis_name=TP_AXIS,
                                            z_loss=z_loss)

    from ..utils.shard_map_compat import shard_map_nocheck

    if head_bias is None:
        return shard_map_nocheck(lambda h, k, tg: body(h, k, None, tg),
                                 topo.mesh,
                                 in_specs=(h_spec, k_spec, tg_spec),
                                 out_specs=tg_spec)(
                                     hidden, head_kernel, targets)
    return shard_map_nocheck(body, topo.mesh,
                             in_specs=(h_spec, k_spec, P(TP_AXIS), tg_spec),
                             out_specs=tg_spec)(
                                 hidden, head_kernel, head_bias, targets)


