"""Vocab-parallel (and sequence-parallel) cross entropy.

Capability parity with ``deepspeed/sequence/cross_entropy.py:1-60``
(``_VocabSequenceParallelCrossEntropy``): compute the LM loss against a
*vocab-sharded* logits tensor without all-gathering the logits. At 32k-256k
vocab the full-vocab logits are the dominant activation at long sequence;
gathering them over tp defeats both TP and Ulysses.

TPU-native design: instead of a torch ``autograd.Function`` with a hand-written
backward, the loss is an ordinary differentiable composition of XLA collectives
inside ``shard_map`` —

  * ``pmax`` over the vocab axis for the stabilising max (stop-gradient: it
    only recentres the exponentials),
  * ``psum`` of the local sum-exp for the global partition function,
  * ``psum`` of the masked target-logit lookup (each target id lives in exactly
    one vocab shard).

JAX transposes ``psum``/``shard_map`` correctly, so ``jax.grad`` produces the
Megatron-style ``softmax - onehot`` backward with the logits *still sharded* —
no custom VJP needed, and XLA fuses the whole thing into the lm-head matmul
epilogue.

The reference's "sequence parallel" flavour additionally all-gathers the
per-token loss along sp; here the loss is returned as a global array whose sp
sharding the caller's reduction consumes directly — the mean is a psum, the
gather never materialises.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.topology import SP_AXIS, TP_AXIS, get_topology

__all__ = [
    "vocab_parallel_cross_entropy",
    "vocab_sequence_parallel_cross_entropy",
    "sharded_lm_loss",
    "resolve_loss_impl",
]


def resolve_loss_impl(impl: Optional[str] = None,
                      vocab_shard: Optional[int] = None) -> str:
    """``auto|xla|fused`` -> the implementation this call should run.

    An explicit (non-auto) argument wins; an ``auto`` argument defers to the
    fleet knob (``ops/fastpath.py``, mapped from the ``training_fastpath``
    config block); a still-``auto`` result resolves to ``fused`` on a real
    accelerator when the vocab shard tiles (``fused_loss_ready``) and to the
    XLA reference otherwise — so CPU test runs keep today's path untouched.
    """
    impl = impl or "auto"
    if impl == "auto":
        from ..ops.fastpath import fastpath

        impl = fastpath("loss_impl")
    if impl == "auto":
        import jax

        from ..ops.pallas.fused_loss import fused_loss_ready

        impl = ("fused" if jax.default_backend() != "cpu"
                and vocab_shard is not None and fused_loss_ready(vocab_shard)
                else "xla")
    return impl


_FUSED_FALLBACK_WARNED = set()


def _warn_fused_fallback(reason: str) -> None:
    if reason in _FUSED_FALLBACK_WARNED:
        return
    _FUSED_FALLBACK_WARNED.add(reason)
    from ..utils.logging import logger

    logger.warning(
        f"loss_impl=fused requested but {reason} — falling back to the XLA "
        f"cross-entropy for these call sites (one-time notice)")


def vocab_parallel_cross_entropy(local_logits, targets, *, axis_name: str = TP_AXIS,
                                 z_loss: float = 0.0):
    """Per-token NLL against vocab-sharded logits. For use inside ``shard_map``.

    Args:
      local_logits: ``[..., V/P]`` — this rank's contiguous vocab shard
        (shard ``i`` covers ids ``[i*V/P, (i+1)*V/P)``).
      targets: ``[...]`` int32 global token ids (same leading shape).
      axis_name: mesh axis the vocab is sharded over.
      z_loss: PaLM-style ``z_loss * log(Z)^2`` regulariser coefficient.

    Returns per-token loss ``[...]`` in float32, identical on every rank of
    ``axis_name`` (it is a psum reduction), differentiable w.r.t. local_logits.
    """
    local_logits = local_logits.astype(jnp.float32)
    vloc = local_logits.shape[-1]
    offset = jax.lax.axis_index(axis_name) * vloc

    # Stabilising max: stop-gradient — it cancels in logZ - target_logit.
    lmax = jax.lax.pmax(
        jax.lax.stop_gradient(jnp.max(local_logits, axis=-1)), axis_name)
    sumexp = jax.lax.psum(
        jnp.sum(jnp.exp(local_logits - lmax[..., None]), axis=-1), axis_name)
    logz = jnp.log(sumexp) + lmax

    t = targets - offset
    in_shard = (t >= 0) & (t < vloc)
    t_clip = jnp.clip(t, 0, vloc - 1)
    tgt = jnp.take_along_axis(local_logits, t_clip[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(jnp.where(in_shard, tgt, jnp.float32(0.0)), axis_name)

    nll = logz - tgt
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logz)
    return nll


def vocab_sequence_parallel_cross_entropy(logits, targets, *, z_loss: float = 0.0,
                                          topo=None):
    """Global-array entry point: ``[B, S, V]`` logits vocab-sharded over tp
    (and batch/seq sharded over dp/sp) -> per-token loss ``[B, S]``.

    Matches ``vocab_sequence_parallel_cross_entropy``
    (reference ``sequence/cross_entropy.py:59``) except the returned loss stays
    a (dp, sp)-sharded global array instead of being explicitly all-gathered —
    under jit the two are the same value.
    """
    topo = topo or get_topology()
    if topo.tp_size == 1:
        logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logits.astype(jnp.float32),
                                  targets[..., None], axis=-1)[..., 0]
        nll = logz - tgt
        return nll + z_loss * jnp.square(logz) if z_loss > 0 else nll

    dp = topo.dp_axes
    lg_spec = P(dp, SP_AXIS, TP_AXIS)  # spec-ok: vocab-parallel CE shard_map wiring: logit grid
    tg_spec = P(dp, SP_AXIS)  # spec-ok: vocab-parallel CE shard_map wiring: target grid

    def body(lg, tg):
        return vocab_parallel_cross_entropy(lg, tg, axis_name=TP_AXIS,
                                            z_loss=z_loss)

    from ..utils.shard_map_compat import shard_map_nocheck

    return shard_map_nocheck(body, topo.mesh,
                             in_specs=(lg_spec, tg_spec),
                             out_specs=tg_spec)(logits, targets)


def sharded_lm_loss(hidden, head_kernel, tokens, *, loss_mask=None,
                    z_loss: float = 0.0, head_bias=None, topo=None,
                    logit_dtype=jnp.float32, loss_impl: Optional[str] = None):
    """Fused vocab-sharded head matmul + cross entropy, next-token shifted.

    ``hidden`` is ``[B, S, E]`` (sp-sharded on S), ``head_kernel`` is
    ``[E, V]`` column-sharded over tp. The ``[B, S, V/tp]`` local logits exist
    only inside the shard_map body, fused by XLA with the reduction — the
    full-vocab activation is never resident. This is the composition the
    reference reaches with Megatron's parallel lm-head + its
    ``_VocabSequenceParallelCrossEntropy``.

    ``loss_impl``: ``auto`` (default — :func:`resolve_loss_impl`), ``xla``
    (today's composition, bit-identical), or ``fused`` — the Pallas online-
    softmax kernel (``ops/pallas/fused_loss.py``): the local logits tile
    never materializes even inside the shard, and the per-shard ``(lse,
    target-logit)`` pair combines with the same tp psum structure, so the
    vocab/sequence-parallel layout is preserved. A head bias or a non-128-
    multiple vocab shard falls back to ``xla`` (one-time warning when fused
    was requested explicitly).
    """
    topo = topo or get_topology()
    vocab = head_kernel.shape[-1]
    vshard = vocab // max(topo.tp_size, 1)
    requested = loss_impl if loss_impl not in (None, "auto") else None
    impl = resolve_loss_impl(loss_impl, vshard)
    if impl == "fused":
        from ..ops.pallas.fused_loss import fused_loss_ready

        reason = None
        if head_bias is not None:
            reason = "the fused kernel takes no head bias"
        elif topo.tp_size > 1 and vocab % topo.tp_size:
            reason = (f"vocab {vocab} does not shard over tp {topo.tp_size}")
        elif not fused_loss_ready(vshard):
            reason = (f"vocab shard {vshard} is not a 128-multiple")
        elif (hidden.shape[0] % topo.axis_size(*topo.dp_axes)
              or hidden.shape[1] % topo.sp_size):
            reason = "the batch does not shard over the dp/sp axes"
        if reason is None:
            return _fused_lm_loss(hidden, head_kernel, tokens,
                                  loss_mask=loss_mask, z_loss=z_loss,
                                  topo=topo)
        if requested == "fused" or _knob_is("fused"):
            _warn_fused_fallback(reason)
        impl = "xla"
    if topo.tp_size != 1:
        if head_kernel.shape[-1] % topo.tp_size != 0:
            raise ValueError(
                f"vocab_parallel_loss needs vocab_size ({head_kernel.shape[-1]}) "
                f"divisible by tp ({topo.tp_size}); pad the vocab up to a "
                "multiple of tp (Megatron pads for the same reason)")
        targets_full, w = _shifted_targets_and_weights(tokens, loss_mask)
        nll = _vocab_sharded_head_nll(hidden, head_kernel, targets_full,
                                      head_bias=head_bias, z_loss=z_loss,
                                      topo=topo, logit_dtype=logit_dtype)
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)
    from ..models.transformer import causal_lm_loss

    logits = hidden.astype(logit_dtype) @ head_kernel.astype(logit_dtype)
    if head_bias is not None:
        logits = logits + head_bias.astype(logit_dtype)
    return causal_lm_loss(logits, tokens, loss_mask=loss_mask, z_loss=z_loss)


def _shifted_targets_and_weights(tokens, loss_mask):
    """Next-token shift keeping S full-length (divisible by sp): targets
    shift with a dummy final position whose weight is zero, and the shift
    folds into the weight mask instead of slicing — shared by the xla tp
    branch and the fused path so the convention cannot drift."""
    targets_full = jnp.concatenate([tokens[:, 1:], tokens[:, -1:]], axis=1)
    w = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
    if loss_mask is not None:
        lm = loss_mask.astype(jnp.float32)
        w = w * jnp.concatenate([lm[:, 1:], jnp.zeros_like(lm[:, -1:])],
                                axis=1)
    return targets_full, w


def _vocab_sharded_head_nll(hidden, head_kernel, targets, *, head_bias,
                            z_loss, topo, logit_dtype):
    """shard_map body: local head matmul fused with the sharded CE."""
    dp = topo.dp_axes
    h_spec = P(dp, SP_AXIS, None)  # spec-ok: fused-head CE shard_map wiring: hidden grid
    k_spec = P(None, TP_AXIS)  # spec-ok: fused-head CE shard_map wiring: vocab-sharded kernel
    tg_spec = P(dp, SP_AXIS)  # spec-ok: fused-head CE shard_map wiring: target grid

    def body(h, k, b, tg):
        lg = h.astype(logit_dtype) @ k.astype(logit_dtype)
        if b is not None:
            lg = lg + b.astype(logit_dtype)
        return vocab_parallel_cross_entropy(lg, tg, axis_name=TP_AXIS,
                                            z_loss=z_loss)

    from ..utils.shard_map_compat import shard_map_nocheck

    if head_bias is None:
        return shard_map_nocheck(lambda h, k, tg: body(h, k, None, tg),
                                 topo.mesh,
                                 in_specs=(h_spec, k_spec, tg_spec),
                                 out_specs=tg_spec)(
                                     hidden, head_kernel, targets)
    return shard_map_nocheck(body, topo.mesh,
                             in_specs=(h_spec, k_spec, P(TP_AXIS), tg_spec),  # spec-ok: fused-head CE shard_map wiring: vocab-sharded bias
                             out_specs=tg_spec)(
                                 hidden, head_kernel, head_bias, targets)


def _knob_is(impl: str) -> bool:
    from ..ops.fastpath import fastpath

    return fastpath("loss_impl") == impl


def _fused_lm_loss(hidden, head_kernel, tokens, *, loss_mask, z_loss, topo):
    """The Pallas fused path, one shard_map for every tp size.

    S stays full-length (divisible by sp): targets shift with a dummy final
    position whose weight is zero (the same trick as the XLA tp branch), so
    the fused kernel sees the unshifted ``[B, S, E]`` layout. At ``tp == 1``
    the body needs no collective at all — the kernel's per-token ``(lse,
    tgt)`` IS the loss; at ``tp > 1`` the pmax/psum combine runs on the tiny
    ``[B, S]`` stats instead of anything vocab-sized.
    """
    targets_full, w = _shifted_targets_and_weights(tokens, loss_mask)
    from ..ops.pallas.fused_loss import fused_vocab_nll
    from ..utils.shard_map_compat import shard_map_nocheck

    dp = topo.dp_axes
    tp = topo.tp_size
    h_spec = P(dp, SP_AXIS, None)  # spec-ok: fused-head CE shard_map wiring: hidden grid
    tg_spec = P(dp, SP_AXIS)  # spec-ok: fused-head CE shard_map wiring: target grid
    k_spec = P(None, TP_AXIS) if tp > 1 else P(None, None)  # spec-ok: fused-head CE shard_map wiring: kernel, tp-gated
    axis = TP_AXIS if tp > 1 else None

    def body(h, k, tg):
        return fused_vocab_nll(h, k, tg, axis_name=axis, z_loss=z_loss)

    nll = shard_map_nocheck(body, topo.mesh,
                            in_specs=(h_spec, k_spec, tg_spec),
                            out_specs=tg_spec)(hidden, head_kernel,
                                               targets_full)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


