"""Ring attention: context parallelism over the ``sp`` mesh axis.

The reference has no ring attention — its long-context scaling is all-to-all
based (Ulysses, ``deepspeed/sequence/layer.py:271``; SURVEY.md §2.3 marks
CP/ring as the TPU build's optional extra). Ulysses' hard limit is
``num_heads % sp == 0``: the exchange re-shards heads, so sp cannot exceed
(or fail to divide) the head count — exactly the regime (few-head GQA models,
very long sequences, large meshes) where context parallelism matters most.

Ring attention (blockwise attention over a ring of devices; Liu et al. 2023,
"Ring Attention with Blockwise Transformers") removes that limit: every rank
keeps ALL heads for its sequence block, KV blocks rotate around the ring via
``ppermute`` (one ICI hop per step — the natural TPU torus pattern), and a
flash-style online softmax accumulates exact attention. Comm volume is
O(S·Hk·D) per rank — independent of the ring size — and the next block's
ppermute is issued before the current block's compute so XLA overlaps
transfer with the matmuls.

Causal masking uses global positions, so a fully-skippable block (all keys
in the future) contributes exp(-inf)=0 work-free; GQA rotates the *unrepeated*
KV blocks (grouped-query einsum locally) so MQA models move 1/H of the bytes.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.topology import SP_AXIS, get_topology

NEG_INF = -1e30


def ring_attention_local(q, k, v, *, axis_size: int, axis_name: str = SP_AXIS,
                         causal: bool = True, scale: Optional[float] = None):
    """Blockwise ring attention for use INSIDE ``shard_map``.

    q: ``[B, L, H, D]`` (this rank's sequence block, all heads);
    k/v: ``[B, L, Hk, D]``. Returns ``[B, L, H, D]``. Exact (online-softmax)
    attention over the global sequence of ``axis_size * L`` tokens.
    """
    b, l, h, d = q.shape
    hk = k.shape[2]
    rep = h // hk
    sc = (1.0 / np.sqrt(d)) if scale is None else float(scale)
    r = lax.axis_index(axis_name)
    pos_q = r * l + jnp.arange(l)                                # global q pos

    qg = q.reshape(b, l, hk, rep, d)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(t, carry):
        kb, vb, m, s_sum, acc = carry
        # issue the rotation FIRST so XLA overlaps the ppermute with compute;
        # the last step needs no rotation (its result would be discarded, but
        # a collective inside the loop body is not DCE-able — skip it)
        kb_next, vb_next = lax.cond(
            t < axis_size - 1,
            lambda ops: (lax.ppermute(ops[0], axis_name, perm),
                         lax.ppermute(ops[1], axis_name, perm)),
            lambda ops: ops, (kb, vb))
        src = (r - t) % axis_size                                # block owner
        pos_k = src * l + jnp.arange(l)
        logits = jnp.einsum("blhrd,bmhd->bhrlm", qg, kb.astype(q.dtype),
                            preferred_element_type=jnp.float32) * sc
        if causal:
            mask = pos_q[:, None] >= pos_k[None, :]              # [l, l]
            logits = jnp.where(mask[None, None, None], logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1, keepdims=True)          # [b,hk,rep,l,1]
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new)
        if causal:  # exp(NEG_INF - NEG_INF) = 1 on fully-masked rows: zero it
            p = jnp.where(logits > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m - m_new)
        s_new = alpha * s_sum + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhrlm,bmhd->bhrld", p.astype(v.dtype),
                        vb.astype(q.dtype), preferred_element_type=jnp.float32)
        acc_new = alpha * acc + pv
        return kb_next, vb_next, m_new, s_new, acc_new

    m0 = jnp.full((b, hk, rep, l, 1), NEG_INF, jnp.float32)
    s0 = jnp.zeros((b, hk, rep, l, 1), jnp.float32)
    a0 = jnp.zeros((b, hk, rep, l, d), jnp.float32)
    _, _, m, s_sum, acc = lax.fori_loop(0, axis_size, step, (k, v, m0, s0, a0))
    safe = jnp.where(s_sum == 0.0, 1.0, s_sum)
    out = (acc / safe).astype(q.dtype)                           # [b,hk,rep,l,d]
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, l, h, d)


def ring_attention(q, k, v, *, apply_pos: Optional[Callable] = None,
                   causal: bool = True, scale: Optional[float] = None):
    """Ring attention over the topology's ``sp`` axis (Ulysses' sibling).

    Inputs are global ``[B, S, H, D]`` arrays whose S dim the engine's batch
    spec shards over ``sp``. ``apply_pos(q, k, positions) -> (q, k)`` applies
    position encoding (RoPE) with GLOBAL positions inside the shard — the
    rank's block offset is not visible outside the shard_map.

    Unlike :func:`~deepspeed_tpu.sequence.layer.ulysses_attention` this places
    no constraint on head counts (works at sp > num_heads) and its per-step
    transfer is one neighbor hop riding the ICI torus.
    """
    topo = get_topology()
    sp = topo.sp_size
    if sp == 1:
        if apply_pos is not None:
            q, k = apply_pos(q, k, None)
        from ..models.transformer import attention_core

        return attention_core(q, k, v, causal=causal, impl="xla", scale=scale)

    h, hk = q.shape[2], k.shape[2]
    tp = topo.tp_size
    heads_axis = "tp" if (tp > 1 and h % tp == 0 and hk % tp == 0) else None
    io_spec = P(topo.dp_axes, SP_AXIS, heads_axis, None)  # spec-ok: ring attention shard_map wiring: heads over tp when divisible

    def body(q_, k_, v_):
        if apply_pos is not None:
            r = lax.axis_index(SP_AXIS)
            pos = (r * q_.shape[1] + jnp.arange(q_.shape[1]))[None, :]
            q_, k_ = apply_pos(q_, k_, pos)
        return ring_attention_local(q_, k_, v_, axis_size=sp, causal=causal,
                                    scale=scale)

    from ..utils.shard_map_compat import shard_map_nocheck

    return shard_map_nocheck(body, topo.mesh,
                             in_specs=(io_spec, io_spec, io_spec),
                             out_specs=io_spec)(q, k, v)
