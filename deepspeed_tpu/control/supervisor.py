"""ControlSupervisor: the object that closes the telemetry -> knobs loop.

One supervisor per engine (and optionally per serving fleet). The engine
calls :meth:`on_step` once per training step (a single attribute check
when control is off); LLMServer engine threads call :meth:`on_serving_tick`
every ``control_interval_steps`` serving steps. Each call reads the live
signals the earlier PRs already publish — the PR 5 ``HealthTable``
straggler/dead verdicts, the PR 10 ``dstpu_mem_*`` device-memory gauges,
the PR 7 ``ServingMetrics`` SLA counters, the PR 4 sentinel's rollbacks —
and runs the rule book (``control/policy.py``) through the
:class:`~.guard.FlapGuard`. Every decision (including guarded no-ops)
lands in the :class:`~.ledger.ControlLedger`, which rides flight dumps,
the Prometheus registry, and the monitor event stream, and is read back
by ``python -m deepspeed_tpu.doctor``.

SPMD note: training-side actions that change the compiled program (the
straggler re-plan, remat, micro-batch) must land on every host. The
signals they key on come from the *shared* beacon table with deterministic
guard state, and the re-resolved plan still rides the planner's rank-0
decision broadcast; nonetheless the supervisor — like the resilience tier
it extends — is wired for single-controller worlds first (the engine
already warns about multi-host snapshot semantics).
"""

import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from ..utils.logging import log_dist
from . import policy
from .guard import FlapGuard
from .ledger import ControlLedger, describe_action


class ControlSupervisor:
    def __init__(self, cfg, *, ledger: Optional[ControlLedger] = None,
                 guard: Optional[FlapGuard] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg                      # runtime.config.ControlConfig
        gc = cfg.guard
        # None-check, not truthiness: a caller-supplied EMPTY ledger (it
        # has __len__) must not be silently replaced — the caller shares
        # it with other recorders (e.g. a FleetManager) and reads it back
        self.ledger = (ControlLedger(max_entries=cfg.ledger_size)
                       if ledger is None else ledger)
        self.guard = guard or FlapGuard(
            trigger_streak=gc.trigger_streak, clear_streak=gc.clear_streak,
            cooldown_s=gc.cooldown_s, budget=gc.budget,
            budget_window_s=gc.budget_window_s, clock=clock)
        self.clock = clock
        self.engine = None
        self.scale_fn: Optional[Callable] = None  # serving scale-out hook
        self._rollbacks: "deque[Tuple[float, int]]" = deque(maxlen=64)
        self._mem_fn: Optional[Callable] = None   # test-injectable probe
        self._mem_stage = 0   # memory-escalation rung (policy.rule_memory)
        self._sla_last: Dict[int, Tuple[int, int]] = {}
        self._budget_noted = False
        self._infeasible_noted: set = set()  # one ledger note per rule
        self._step_i = 0

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    @classmethod
    def for_engine(cls, engine, cfg) -> "ControlSupervisor":
        sup = cls(cfg)
        sup.attach_engine(engine)
        return sup

    def attach_engine(self, engine) -> "ControlSupervisor":
        """Wire into one engine: the resilience manager reports rollbacks,
        the telemetry manager carries the ledger in flight dumps and hosts
        the ``dstpu_control_actions_total`` counter, and ``Control/*``
        monitor events ride the engine's existing monitor fan-out."""
        self.engine = engine
        rz = getattr(engine, "resilience", None)
        if rz is not None:
            rz._control = self
        tm = getattr(engine, "telemetry", None)
        if tm is not None:
            tm.attach_control(self)
            self.ledger.bind_counter(tm.registry.counter(
                "dstpu_control_actions_total",
                "automated control-plane actions by kind"))
        # read engine.monitor at emit time: tests (and late configuration)
        # swap the monitor after init
        self.ledger.bind_monitor(
            lambda events: engine.monitor.write_events(events)
            if getattr(engine, "monitor", None) is not None else None)
        return self

    def attach_server(self, server, *,
                      interval_steps: Optional[int] = None,
                      scale_fn: Optional[Callable] = None):
        """Wire into one LLMServer: its engine thread ticks
        :meth:`on_serving_tick` every ``control_interval_steps`` serving
        steps. ``scale_fn(supervisor)`` — when provided — is the scale-out
        actuator (e.g. build a replica and ``router.add_replica`` it);
        without one, sustained SLA pressure sheds load instead."""
        server.control = self
        if interval_steps is not None:
            server.control_interval_steps = int(interval_steps)
        if scale_fn is not None:
            self.scale_fn = scale_fn
        if self.ledger._counter is None:
            try:
                from ..telemetry import get_registry, telemetry_active

                if telemetry_active():
                    self.ledger.bind_counter(get_registry().counter(
                        "dstpu_control_actions_total",
                        "automated control-plane actions by kind"))
            except Exception:
                pass  # swallow-ok: optional telemetry binding must never block serving attach
        return server

    # ------------------------------------------------------------------
    # signal taps (policy.py reads through these; tests inject here)
    # ------------------------------------------------------------------
    def straggler_rows(self):
        """``[(rank, ratio)]`` for every straggler the HealthTable calls
        out — read from the rows the resilience heartbeat tick ALREADY
        fetched this beat (``ResilienceManager.last_health``), never a
        fresh transport read: the control loop runs every step, and a
        per-step ``read_all()`` against a bucket transport would put
        network I/O on the training hot path the resilience tier
        deliberately paces by ``heartbeat.interval_steps``."""
        rz = getattr(self.engine, "resilience", None)
        rows = getattr(rz, "last_health", None) if rz is not None else None
        if not rows:
            return []
        return [(r.rank, r.ratio) for r in rows if r.straggler]

    def can_replan(self) -> bool:
        """Static feasibility of the straggler re-plan on THIS engine:
        planner on and a re-plannable DP-grad site resolved. Checked
        BEFORE the guard so a permanently impossible action never charges
        the global budget."""
        try:
            from ..comm.planner import planner_active

            return bool(planner_active()) and bool(
                getattr(self.engine, "_dp_grad_site_eligible", False))
        except Exception:
            return False

    def note_infeasible(self, action: str, rule: str, *, step: int,
                        signal: str, reason: str, outcome: str) -> None:
        """Record a statically-impossible actuation ONCE per rule — the
        operator should see why the supervisor stands down, but neither a
        ledger entry per step nor a budget charge for a guaranteed no-op."""
        if rule in self._infeasible_noted:
            return
        self._infeasible_noted.add(rule)
        self.ledger.record(action, step=step, rule=rule, signal=signal,
                           reason=reason, outcome=outcome)

    def slow_link_axes(self) -> Tuple[str, ...]:
        """Which mesh axes carry the straggler's traffic: the operator
        override wins; else the fingerprint's DCN axes (a slow host sits
        across the slice boundary); else the outermost dp axis of a
        multi-axis dp span (the cross-host hop by construction). A
        single-axis span has no alternative route — empty."""
        sc = self.cfg.supervisor
        if sc.replan_axes:
            return tuple(sc.replan_axes)
        try:
            from ..comm.planner import get_planner, planner_active

            if planner_active():
                fp = get_planner().fingerprint
                if fp.dcn_axes:
                    return tuple(fp.dcn_axes)
        except Exception:
            pass  # swallow-ok: planner fingerprint is an optional hint; fall through to topology
        topo = getattr(self.engine, "topo", None)
        if topo is not None and len(topo.dp_axes) > 1:
            return (topo.dp_axes[0],)
        return ()

    def mem_sample(self) -> Optional[Dict[str, int]]:
        """The newest device-memory gauge sample: the telemetry manager's
        last per-step read (one step stale by design — same contract as
        the sentinel's delayed metrics), or an injected probe."""
        if self._mem_fn is not None:
            return self._mem_fn()
        tm = getattr(self.engine, "telemetry", None)
        return getattr(tm, "last_mem", None) if tm is not None else None

    def note_rollback(self, step: int) -> None:
        """Called by ResilienceManager._rollback — the rollback-rate signal."""
        self._rollbacks.append((self.clock(), int(step)))

    def recent_rollbacks(self, window_s: float):
        now = self.clock()
        return [s for t, s in self._rollbacks if now - t <= window_s]

    def sla_delta(self, sid: int, violations: int,
                  tracked: int) -> Tuple[int, int]:
        last_v, last_t = self._sla_last.get(sid, (0, 0))
        self._sla_last[sid] = (int(violations), int(tracked))
        return int(violations) - last_v, int(tracked) - last_t

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    def on_step(self, step: Optional[int] = None) -> None:
        """Per-training-step hook (engine ``_train_batch_inner``): evaluate
        every training-side rule. Pure host work — never touches device
        state except through the actuators a fired rule invokes. ``step``
        is the EXECUTING step number (the engine passes the pre-increment
        N its spans, flight ring, and watchdog all stamp, so ledger
        entries cross-correlate with the other post-mortem surfaces)."""
        engine = self.engine
        if engine is None:
            return
        self._step_i += 1
        sc = self.cfg.supervisor
        n = max(1, int(sc.interval_steps))
        if self._step_i % n:
            return
        step = engine.global_steps if step is None else int(step)
        if sc.straggler_replan:
            policy.rule_straggler(self, step)
        if sc.memory_guard:
            policy.rule_memory(self, step)
        if sc.rollback_degrade:
            policy.rule_rollbacks(self, step)
        if sc.integrity_guard:
            policy.rule_integrity(self, step)
        self._note_budget(step)

    def on_serving_tick(self, server) -> None:
        """Per-serving-interval hook (LLMServer engine thread)."""
        if self.cfg.supervisor.sla_guard:
            policy.rule_sla(self, server)
            self._note_budget(server._steps)

    def _note_budget(self, step: int) -> None:
        if self.guard.budget_exhausted_observed and not self._budget_noted:
            self._budget_noted = True
            gc = self.cfg.guard
            entry = self.ledger.record(
                "budget_exhausted", step=step, rule="budget",
                signal="global action budget",
                reason=f"action budget ({gc.budget} per "
                       f"{gc.budget_window_s:g}s) exhausted — the "
                       "supervisor observes but no longer acts until the "
                       "window drains", outcome="skipped:budget")
            log_dist(f"control: {describe_action(entry.to_dict())}")
