"""Autotuner winner cache: tuned knobs keyed by mesh fingerprint digest.

Tuning is only worth its probe budget if it runs ONCE per topology. The
winner cache lives beside the comm-plan cache (same directory resolution:
``comm_planner.cache_dir`` > ``$DSTPU_PLAN_CACHE`` >
``~/.cache/deepspeed_tpu/comm_plans``) as ``autotune_<digest>.json``, one
file per :class:`~deepspeed_tpu.comm.planner.topo.MeshFingerprint` digest —
so a changed mesh (different chip count, different axis split, a forced
DCN override) can NEVER replay a stale winner, and a cold restart on the
same mesh reuses the recorded winner without a single probe.

Inside one mesh's file, winners are keyed by a *space signature* — a hash
of the searched dimensions, their candidate names, and the metric — so
re-tuning with a different search space records a sibling entry instead of
clobbering (or wrongly satisfying) the old one.

Writes use the plan cache's discipline: flock-serialized read-merge-write,
tmp + atomic rename. A corrupt or foreign file reads as a miss.
"""

import hashlib
import json
import os
import tempfile
import time
from typing import Any, Dict, Optional

from ..comm.planner.cache import default_cache_dir
from ..comm.planner.topo import MeshFingerprint

_FILE_VERSION = 1


def space_signature(dims: Dict[str, Any], metric: str) -> str:
    """Stable hash of the searched space: dimension names + the candidate
    names inside each + the optimization metric."""
    blob = json.dumps({"dims": {k: sorted(v) if isinstance(v, (list, tuple))
                                else v for k, v in sorted(dims.items())},
                       "metric": metric}, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class WinnerCache:
    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or default_cache_dir()

    def path_for(self, fp: MeshFingerprint) -> str:
        return os.path.join(self.cache_dir, f"autotune_{fp.digest()}.json")

    def _read(self, fp: MeshFingerprint) -> Dict[str, Any]:
        try:
            with open(self.path_for(fp)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}
        if not isinstance(doc, dict) or doc.get("fingerprint") != fp.digest():
            return {}
        winners = doc.get("winners")
        return winners if isinstance(winners, dict) else {}

    # ------------------------------------------------------------------
    def lookup(self, fp: MeshFingerprint, space_sig: str
               ) -> Optional[Dict[str, Any]]:
        """The recorded winner for (mesh digest, search space), or None."""
        w = self._read(fp).get(space_sig)
        return dict(w) if isinstance(w, dict) else None

    def store(self, fp: MeshFingerprint, space_sig: str,
              winner: Dict[str, Any]) -> str:
        """Merge one winner in (flock + tmp/rename, the PlanCache recipe)
        and return the file path."""
        os.makedirs(self.cache_dir, exist_ok=True)
        path = self.path_for(fp)
        lock = open(path + ".lock", "w")
        try:
            try:
                import fcntl

                fcntl.flock(lock, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # no flock: best-effort merge
            winners = self._read(fp)
            winners[space_sig] = {**winner, "recorded_wall_time": time.time()}
            body = {"version": _FILE_VERSION, "fingerprint": fp.digest(),
                    "mesh": fp.to_dict(), "winners": winners}
            fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(body, f, indent=1, sort_keys=True)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        finally:
            lock.close()
        return path
