"""Autotuner v2: multi-dimensional knob search with cached per-mesh winners.

The seed autotuner (``autotuning/autotuner.py``) searches ZeRO stage x
micro-batch. This generalization searches the knob space the later PRs
actually added — gradient accumulation, rematerialization policy, the
``training_fastpath`` fused kernels, ``compressed_collectives`` transport
— as the cartesian product of per-dimension candidates, evaluated with the
SAME in-process engine-warmup probe the seed tuner uses (build an engine,
JIT in warmup, time steady-state steps), driven by the existing
``autotuning/tuner.py`` search strategies (the model-based tuner's early
stop is what makes the probe count beat exhaustive grid).

Two extras the flat grid never had:

- **collective-program probes** — when the mesh (or a forced
  ``comm_planner.dcn_axes`` override) has cross-slice axes, the DP-grad
  site's synthesized multi-phase programs are timed through the SAME
  microbenchmark executor the planner's measure mode runs
  (``comm/planner/microbench.py``), and the winning program rides in the
  winner record;
- **per-mesh winner cache** — winners persist beside the comm-plan cache
  keyed by :class:`MeshFingerprint` digest (``control/winners.py``), so a
  cold restart on the same mesh applies the recorded winner with ZERO
  probes (``probes_run == 0``, ``from_cache == True``) and a changed mesh
  re-tunes from scratch.
"""

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..autotuning.autotuner import Experiment, _merge
from ..utils.logging import logger
from .winners import WinnerCache, space_signature

# ---------------------------------------------------------------------------
# the knob space: dimension name -> [(candidate name, config overrides)]
# ---------------------------------------------------------------------------


def dim_candidates(name: str, base_config: Dict) -> List[Tuple[str, Dict]]:
    base_gas = int(base_config.get("gradient_accumulation_steps", 1) or 1)
    base_mbs = int(base_config.get("train_micro_batch_size_per_gpu", 1) or 1)
    if name == "gas":
        vals = sorted({1, max(1, base_gas), base_gas * 2})
        return [(f"gas{g}", {"gradient_accumulation_steps": g,
                             "train_batch_size": None}) for g in vals]
    if name == "micro_batch":
        vals = sorted({max(1, base_mbs // 2), base_mbs, base_mbs * 2})
        return [(f"mbs{m}", {"train_micro_batch_size_per_gpu": m,
                             "train_batch_size": None}) for m in vals]
    if name == "stage":
        return [(f"z{s}", {"zero_optimization": {"stage": s}})
                for s in (0, 1, 2, 3)]
    if name == "remat":
        # consumed by the engine's whole-loss checkpoint wrap (engine_wrap
        # opts in — per-layer compat-API remat stays the model's): None =
        # no remat, dots_saveable = recompute everything but matmul
        # outputs, nothing_saveable = full remat (max memory headroom)
        return [("remat-off",
                 {"activation_checkpointing": {"policy": None,
                                               "engine_wrap": True}}),
                ("remat-dots",
                 {"activation_checkpointing": {"policy": "dots_saveable",
                                               "engine_wrap": True}}),
                ("remat-full",
                 {"activation_checkpointing": {"policy": "nothing_saveable",
                                               "engine_wrap": True}})]
    if name == "fastpath":
        return [("fp-auto", {"training_fastpath": {
                    "attn_impl": "auto", "loss_impl": "auto"}}),
                ("fp-xla", {"training_fastpath": {
                    "attn_impl": "xla", "loss_impl": "xla"}})]
    if name == "compression":
        return [("cc-none", {"compressed_collectives": {"mode": "none"}}),
                ("cc-int8", {"compressed_collectives": {"mode": "int8"}})]
    raise ValueError(f"unknown autotune dimension {name!r}; known: "
                     "gas, micro_batch, stage, remat, fastpath, compression")


def _combine(a: Dict, b: Dict) -> Dict:
    """Deep-merge override dicts KEEPING ``None`` values: a ``None`` is the
    pop-marker ``_merge`` consumes when the overrides finally land on the
    base config (``"train_batch_size": None`` must survive combination, or
    a base carrying a resolved batch triangle breaks every gas/micro
    candidate at ``finalize``)."""
    out = dict(a)
    for k, v in b.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _combine(out[k], v)
        else:
            out[k] = v
    return out


def build_space(base_config: Dict,
                dims: Sequence[str]) -> List[Experiment]:
    """Cartesian product of the per-dimension candidates as Experiments
    (the seed tuner's unit of work, so ``autotuning/tuner.py`` strategies
    drive this space unchanged)."""
    per_dim = [dim_candidates(d, base_config) for d in dims]
    out = []
    for combo in itertools.product(*per_dim):
        name = "_".join(n for n, _ in combo)
        overrides: Dict[str, Any] = {}
        for _, ov in combo:
            overrides = _combine(overrides, ov)
        out.append(Experiment(name=name, overrides=overrides))
    return out


# ---------------------------------------------------------------------------
# collective-program probes (the planner-variant dimension)
# ---------------------------------------------------------------------------


def probe_collective_programs(n_elems: int, *, axes: Sequence[str],
                              reps: int = 2, repeats: int = 2,
                              max_elems: int = 1 << 14
                              ) -> Optional[Dict[str, Any]]:
    """Time the DP-grad site's flat implementations against the program
    compiler's searched beam through the planner's OWN microbenchmark
    executor (``comm/planner/microbench.benchmark_site`` — measure mode's
    ground truth, so the autotuner's program verdicts and the planner's
    agree by construction). Returns ``{winner, timings_us}`` or None when
    the fingerprint has no cross-slice axes to compile programs over."""
    from ..comm.planner import (benchmark_site, compile_programs,
                                get_planner, make_site, program_summary)

    planner = get_planner()
    site = make_site(op="all_reduce", shape=(int(n_elems),), dtype="float32",
                     axes=axes, consumer="dp-grad")
    programs = [prog for prog, _ in
                compile_programs(site, planner.cost, block=planner.block,
                                 beam_width=planner.beam_width)]
    if not programs:
        return None
    cands: List[Tuple[str, Optional[tuple]]] = [("xla", None),
                                                ("int8", None)]
    cands += [(f"program:{program_summary(p)}", p) for p in programs]
    timings: Dict[str, float] = {}
    for name, prog in cands:
        impl = "program" if prog is not None else name
        try:
            t = benchmark_site(site, impl, block=planner.block, program=prog,
                               reps=reps, repeats=repeats,
                               max_elems=max_elems)
        except Exception as e:  # a candidate that fails to build loses
            logger.warning(f"autotune: program probe {name} failed: "
                           f"{type(e).__name__}: {e}")
            continue
        timings[name] = round(t * 1e6, 3)
    if not timings:
        return None
    winner = min(timings, key=timings.get)
    return {"winner": winner, "timings_us": timings,
            "site": site.signature()}


# ---------------------------------------------------------------------------
# the tuner
# ---------------------------------------------------------------------------


class ControlAutotuner:
    """Search the generalized knob space; cache the winner per mesh.

    ``tune(loss_fn, params, batch_fn)`` returns the best full config (base
    + winning overrides). ``probes_run`` counts engine probes actually
    executed — the number the winner-cache reuse test asserts is ZERO on a
    warm mesh, and that the fewer-than-grid guarantee is stated in terms
    of (``probes_run < grid_size`` under the model-based tuner).
    """

    def __init__(self, base_config: Dict, *,
                 dims: Sequence[str] = ("gas", "remat", "fastpath",
                                        "compression"),
                 metric: str = "throughput",
                 warmup_steps: int = 1, measure_steps: int = 2,
                 tuner_type: str = "model", early_stop: int = 3,
                 use_cache: bool = True, cache_dir: Optional[str] = None,
                 probe_programs: bool = True,
                 hbm_bytes: Optional[float] = None, seed: int = 0):
        self.base_config = dict(base_config)
        self.dims = tuple(dims)
        self.metric = metric
        self.warmup_steps = int(warmup_steps)
        self.measure_steps = int(measure_steps)
        self.tuner_type = tuner_type
        self.early_stop = int(early_stop)
        self.seed = int(seed)
        self.hbm_bytes = hbm_bytes
        self.probe_programs = bool(probe_programs)
        self.cache = WinnerCache(cache_dir) if use_cache else None
        self.space_sig = space_signature(
            {d: [n for n, _ in dim_candidates(d, self.base_config)]
             for d in self.dims}, metric)
        self.results: List[Experiment] = []
        self.probes_run = 0
        self.grid_size = 0
        self.from_cache = False
        self.best: Optional[Dict[str, Any]] = None
        self.program_probe: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, config, base_config: Optional[Dict] = None,
                    **overrides) -> "ControlAutotuner":
        """Build from the ``control.autotune`` config block — the knobs
        documented in ``docs/config.md`` land here. ``config`` may be a
        full ``DeepSpeedTPUConfig`` (its dict form then doubles as the
        base the candidates override), a ``ControlConfig``, a
        ``ControlAutotuneConfig``, or a plain dict of its fields;
        keyword ``overrides`` win over the block."""
        at = config
        base = base_config
        if hasattr(at, "control"):          # DeepSpeedTPUConfig
            if base is None:
                base = at.to_dict()
            at = at.control
        if hasattr(at, "autotune"):         # ControlConfig
            at = at.autotune
        if isinstance(at, dict):
            from ..runtime.config import ControlAutotuneConfig

            at = ControlAutotuneConfig.from_dict(at)
        if base is None:
            raise ValueError(
                "ControlAutotuner.from_config needs base_config when "
                "given only the autotune block (there is no base ds "
                "config to overlay candidates on)")
        kw = dict(dims=tuple(at.dims), metric=at.metric,
                  warmup_steps=at.warmup_steps,
                  measure_steps=at.measure_steps, tuner_type=at.tuner_type,
                  early_stop=at.early_stop, use_cache=at.use_cache,
                  cache_dir=at.cache_dir, probe_programs=at.probe_programs)
        kw.update(overrides)
        return cls(dict(base), **kw)

    def _fingerprint(self):
        from ..comm.planner import MeshFingerprint

        return MeshFingerprint.capture()

    def summary(self) -> str:
        lines = [f"{'experiment':<40} {self.metric:>14}"]
        for e in self.results:
            val = (f"{e.metric_value:.2f}" if e.metric_value is not None
                   else f"FAILED ({e.error})")
            lines.append(f"{e.name:<40} {val:>14}")
        return "\n".join(lines)

    # ------------------------------------------------------------------
    def tune(self, loss_fn: Callable, params: Any,
             batch_fn: Callable[[int], Any]) -> Dict:
        """Probe (or recall) the winner and return the merged best config.

        ``batch_fn(global_batch_size) -> batch`` — the same contract as the
        seed tuner; each probe builds a fresh engine through the normal
        ``deepspeed_tpu.initialize`` path, so a candidate exercises exactly
        the code the winning config will run."""
        import time as _time

        import jax
        import numpy as np

        fp = self._fingerprint()
        if self.cache is not None:
            hit = self.cache.lookup(fp, self.space_sig)
            if hit is not None:
                self.from_cache = True
                self.best = hit
                self.grid_size = int(hit.get("grid_size", 0))
                self.program_probe = hit.get("program_probe")
                logger.info(
                    f"autotune: mesh {fp.digest()} has a cached winner "
                    f"{hit.get('name')} ({hit.get('metric_value')}) — "
                    f"0 probes")
                return _merge(self.base_config, hit.get("overrides", {}))

        import deepspeed_tpu as ds

        from ..autotuning.tuner import TUNERS
        from ..runtime.zero.memory_estimators import \
            estimate_zero_model_states_mem_needs

        exps = build_space(self.base_config, self.dims)
        self.grid_size = len(exps)
        if self.hbm_bytes is not None:
            # memory-prune like the seed tuner: a stage that cannot fit is
            # not worth a probe (stage only varies when "stage" is a dim)
            ndev = len(jax.devices())
            pcount = sum(int(np.prod(l.shape))
                         for l in jax.tree.leaves(params)
                         if hasattr(l, "shape"))
            keep = []
            for e in exps:
                stage = (e.overrides.get("zero_optimization", {})
                         .get("stage",
                              self.base_config.get("zero_optimization", {})
                              .get("stage", 0)))
                est = estimate_zero_model_states_mem_needs(pcount, stage, ndev)
                if est["total_bytes"] <= self.hbm_bytes:
                    keep.append(e)
            exps = keep or exps[:1]
        if not exps:
            raise RuntimeError("autotune: empty search space")

        def evaluate(exp: Experiment) -> Optional[float]:
            cfg = _merge(self.base_config, exp.overrides)
            self.probes_run += 1
            try:
                engine, _, _, _ = ds.initialize(
                    model=loss_fn, model_parameters=params, config=cfg)
                gbs = engine.train_batch_size
                for _ in range(self.warmup_steps):
                    engine.train_batch(batch=batch_fn(gbs))
                t0 = _time.perf_counter()
                for _ in range(max(1, self.measure_steps)):
                    engine.train_batch(batch=batch_fn(gbs))
                # the probe is wall-clock: land the dispatched work before
                # stopping the timer or async dispatch flatters every arm
                jax.block_until_ready(engine.state.params)
                dt = ((_time.perf_counter() - t0)
                      / max(1, self.measure_steps))
                exp.metric_value = (gbs / dt if self.metric == "throughput"
                                    else -dt)
                logger.info(f"autotune: {exp.name} -> "
                            f"{exp.metric_value:.2f} ({self.metric})")
            except Exception as e:  # OOM / invalid combo: learnable failure
                exp.error = str(e).splitlines()[0][:120]
                logger.warning(f"autotune: {exp.name} failed: {exp.error}")
            self.results.append(exp)
            return exp.metric_value

        tuner = TUNERS[self.tuner_type](exps, metric=self.metric,
                                        early_stop=self.early_stop,
                                        seed=self.seed)
        best = tuner.tune(evaluate)
        if best is None:
            raise RuntimeError("autotune: every probe failed\n"
                               + self.summary())
        if self.probe_programs:
            n_elems = sum(int(np.prod(l.shape))
                          for l in jax.tree.leaves(params)
                          if hasattr(l, "shape"))
            from ..comm.planner import get_planner

            pl = get_planner()
            dp_axes = tuple(a for a, s in pl.fingerprint.axis_sizes
                            if s > 1 and a in ("dp_outer", "ep"))
            if dp_axes:
                try:
                    self.program_probe = probe_collective_programs(
                        n_elems, axes=dp_axes)
                except Exception as e:
                    logger.warning(f"autotune: program probes skipped: {e!r}")
        self.best = {
            "name": best.name,
            "overrides": best.overrides,
            "metric": self.metric,
            "metric_value": best.metric_value,
            "probes": tuner.trials_run,
            "grid_size": self.grid_size,
            "dims": list(self.dims),
            "program_probe": self.program_probe,
        }
        if self.cache is not None:
            try:
                self.cache.store(fp, self.space_sig, self.best)
            except OSError:
                pass  # read-only FS: winner still applies in-memory
        logger.info(f"autotune: winner {best.name} after "
                    f"{tuner.trials_run}/{self.grid_size} probes")
        return _merge(self.base_config, best.overrides)
