"""Flap guard: hysteresis + cooldown + budget for automated actions.

A control loop that reacts instantly to every signal edge will *flap*: an
alternating straggler/clear verdict would re-plan collectives every step,
each re-plan costing a retrace, until the cure is worse than the disease.
Every rule the supervisor runs is therefore filtered through this state
machine, which only lets an action fire when ALL of:

- **hysteresis** — the signal has been asserted for ``trigger_streak``
  consecutive observations (a one-observation blip never acts), and the
  rule has re-armed: after a firing, the signal must first be observed
  *clear* for ``clear_streak`` consecutive observations before the same
  rule may fire again (a signal that never clears fires once, not forever);
- **cooldown** — at least ``cooldown_s`` since this rule last fired
  (re-arming via the clear streak still respects the cooldown);
- **budget** — fewer than ``budget`` firings across ALL rules within the
  trailing ``budget_window_s`` (the global circuit breaker: a pathological
  environment exhausts the budget and the fleet keeps running on whatever
  knobs it has, loudly, instead of thrashing).

Stdlib-only, clock-injectable, and deliberately free of any engine
knowledge so the unit tests exercise the exact state machine production
runs (``tests/unit/test_control.py``).
"""

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional


class _RuleState:
    __slots__ = ("assert_streak", "clear_streak", "latched", "last_fire",
                 "fires")

    def __init__(self):
        self.assert_streak = 0
        self.clear_streak = 0
        self.latched = False      # fired; needs clear_streak clears to re-arm
        self.last_fire: Optional[float] = None
        self.fires = 0


class FlapGuard:
    def __init__(self, *, trigger_streak: int = 2, clear_streak: int = 2,
                 cooldown_s: float = 120.0, budget: int = 8,
                 budget_window_s: float = 3600.0,
                 clock: Callable[[], float] = time.monotonic):
        self.trigger_streak = max(1, int(trigger_streak))
        self.clear_streak = max(1, int(clear_streak))
        self.cooldown_s = float(cooldown_s)
        self.budget = int(budget)
        self.budget_window_s = float(budget_window_s)
        self.clock = clock
        self._rules: Dict[str, _RuleState] = {}
        self._fire_times: "deque[float]" = deque()
        self._lock = threading.Lock()
        self.budget_exhausted_observed = False  # ledger records this ONCE

    # ------------------------------------------------------------------
    def _state(self, rule: str) -> _RuleState:
        st = self._rules.get(rule)
        if st is None:
            st = self._rules[rule] = _RuleState()
        return st

    def _budget_left(self, now: float) -> int:
        while self._fire_times and \
                now - self._fire_times[0] > self.budget_window_s:
            self._fire_times.popleft()
        return self.budget - len(self._fire_times)

    # ------------------------------------------------------------------
    def should_fire(self, rule: str, asserted: bool, *,
                    restorative: bool = False) -> bool:
        """Feed one observation of ``rule``'s signal; True means: act NOW
        (the firing is recorded — cooldown starts, the budget is charged,
        and the rule latches until the signal clears).

        ``restorative`` marks actions that UNDO an earlier degradation
        (un-shed, restore admission): they keep the hysteresis/cooldown/
        latch semantics but neither consult nor charge the global budget —
        an exhausted budget must never leave a recovered system stuck in
        its degraded configuration."""
        now = self.clock()
        with self._lock:
            st = self._state(rule)
            if asserted:
                st.assert_streak += 1
                st.clear_streak = 0
            else:
                st.clear_streak += 1
                st.assert_streak = 0
                if st.latched and st.clear_streak >= self.clear_streak:
                    st.latched = False  # re-armed
                return False
            if st.latched:
                return False
            if st.assert_streak < self.trigger_streak:
                return False
            if st.last_fire is not None and \
                    now - st.last_fire < self.cooldown_s:
                return False
            if not restorative:
                if self._budget_left(now) <= 0:
                    self.budget_exhausted_observed = True
                    return False
            # fire
            st.latched = True
            st.last_fire = now
            st.fires += 1
            st.assert_streak = 0
            if not restorative:
                self._fire_times.append(now)
            return True

    # ------------------------------------------------------------------
    def rearm(self, prefix: str = "") -> int:
        """Forcibly re-arm latched rules whose name starts with ``prefix``
        (all rules for ""). Returns how many were re-armed.

        The latch-until-clear hysteresis assumes the world the rule fired
        in still exists: a signal that never clears keeps the rule latched
        because re-firing would just repeat the same actuation. After a
        TOPOLOGY change — a replica died, capacity freed up — that memory
        is stale: an ``sla_pressure`` rule that latched on a scale-out
        attempt rejected at capacity must not block the first scale-out of
        the new, smaller fleet. Cooldown and budget still apply to the
        next firing; only the clear-streak requirement is waived."""
        n = 0
        with self._lock:
            for name, st in self._rules.items():
                if name.startswith(prefix) and st.latched:
                    st.latched = False
                    st.assert_streak = 0
                    n += 1
        return n

    # ------------------------------------------------------------------
    def fires(self, rule: str) -> int:
        with self._lock:
            st = self._rules.get(rule)
            return st.fires if st else 0

    def total_fires(self) -> int:
        with self._lock:
            return sum(st.fires for st in self._rules.values())

    def budget_left(self) -> int:
        with self._lock:
            return max(0, self._budget_left(self.clock()))

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Serializable guard state (rides the control ledger dumps)."""
        with self._lock:
            return {rule: {"fires": st.fires, "latched": st.latched,
                           "assert_streak": st.assert_streak,
                           "clear_streak": st.clear_streak}
                    for rule, st in self._rules.items()}
