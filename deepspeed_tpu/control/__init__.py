"""Self-driving fleet control plane: close the loop from telemetry to knobs.

Every signal a production operator reads (planner microbench timings,
HealthTable straggler/dead verdicts, ``dstpu_mem_*`` gauges, ServingMetrics
SLA counters, sentinel rollbacks, doctor verdicts) and every knob they turn
(planner impl/program selection, compression mode, fastpath, remat,
micro-batch/GAS, replica drain/scale, degraded mode) existed before this
subsystem — but a human sat between them. This package is the loop closure,
in two halves sharing one decision ledger:

- **Autotuner v2** (:mod:`.autotune`) — offline-ish: short measured probes
  over the generalized knob space {GAS, remat policy, training_fastpath,
  compressed_collectives, planner program variants}, winners cached per
  mesh-fingerprint digest beside the comm-plan cache (:mod:`.winners`) so a
  restart on the same mesh re-applies them with zero probes.
- **Supervisor policy** (:mod:`.supervisor`, rule book in :mod:`.policy`) —
  online: reacts to live signals through a hysteresis/cooldown/budget flap
  guard (:mod:`.guard`); every automated decision is a ledger entry
  (:mod:`.ledger`) that rides flight dumps, Prometheus
  (``dstpu_control_actions_total``), ``Control/*`` monitor events, and the
  doctor's post-mortem report.

Gated behind the ``control:`` config block — disabled (the default)
constructs nothing and engine stepping is bit-identical. See
``docs/autotuning.md``.
"""

from .autotune import (ControlAutotuner, build_space, dim_candidates,
                       probe_collective_programs)
from .guard import FlapGuard
from .ledger import ControlAction, ControlLedger, describe_action
from .policy import POLICY_TABLE, RULE_NAMES
from .supervisor import ControlSupervisor
from .winners import WinnerCache, space_signature

__all__ = ["ControlSupervisor", "ControlAutotuner", "ControlLedger",
           "ControlAction", "FlapGuard", "WinnerCache", "space_signature",
           "describe_action", "build_space", "dim_candidates",
           "probe_collective_programs", "POLICY_TABLE", "RULE_NAMES"]
