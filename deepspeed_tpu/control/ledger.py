"""Control ledger: every automated decision, recorded where post-mortems look.

The control plane's contract with the operator is *explainability*: a knob
that moves by itself MUST leave a record of what moved it, or the fleet
becomes undebuggable. Each supervisor action (and each autotuner
application) appends one :class:`ControlAction` here, and the entry fans
out to every observability surface the repo already has:

- the bounded in-memory ring rides every telemetry **flight dump**
  (``TelemetryManager.flight_dump`` attaches ``snapshot()`` under the
  ``control`` key), so ``python -m deepspeed_tpu.doctor`` prints
  "supervisor action" lines beside its verdicts;
- ``dstpu_control_actions_total{action=...}`` in the Prometheus
  **registry** (when the telemetry spine is live);
- ``Control/<action>`` **monitor events** through the existing
  ``Monitor.write_events`` fan-out (TensorBoard / W&B / CSV / JSONL).

Stdlib-only; the registry/monitor hooks are injected callables so the
ledger works (and is unit-testable) without either subsystem.
"""

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class ControlAction:
    """One automated decision. ``outcome`` records what actually happened
    (``ok`` / ``skipped:<why>`` / ``failed:<why>``) — a rule that fired but
    found nothing to actuate is still a ledger entry, because the operator
    debugging a flapping signal needs to see the no-ops too."""
    seq: int
    step: int
    wall_time: float
    action: str            # e.g. straggler_replan, raise_remat, serving_shed
    rule: str              # guard rule that fired (usually == action)
    signal: str            # the observed signal, human-readable
    reason: str            # why the rule decided to act
    params: Dict[str, Any] = field(default_factory=dict)
    outcome: str = "ok"

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


class ControlLedger:
    def __init__(self, *, max_entries: int = 256,
                 clock: Callable[[], float] = time.time):
        self._ring: "deque[ControlAction]" = deque(
            maxlen=max(1, int(max_entries)))
        self._lock = threading.Lock()
        self.clock = clock
        self._seq = 0
        self.total = 0
        # injected sinks: set by ControlSupervisor wiring
        self._counter = None          # telemetry Counter (inc(action=...))
        self._emit: Optional[Callable[[List], None]] = None  # monitor events

    # -- wiring ---------------------------------------------------------
    def bind_counter(self, counter) -> None:
        """A ``dstpu_control_actions_total`` Counter (telemetry registry)."""
        self._counter = counter

    def bind_monitor(self, emit: Callable[[List], None]) -> None:
        """``Monitor.write_events``-compatible callable for Control/* events."""
        self._emit = emit

    # -- recording ------------------------------------------------------
    def record(self, action: str, *, step: int, rule: Optional[str] = None,
               signal: str = "", reason: str = "",
               params: Optional[Dict[str, Any]] = None,
               outcome: str = "ok") -> ControlAction:
        with self._lock:
            self._seq += 1
            entry = ControlAction(seq=self._seq, step=int(step),
                                  wall_time=float(self.clock()),
                                  action=str(action), rule=rule or str(action),
                                  signal=signal, reason=reason,
                                  params=dict(params or {}), outcome=outcome)
            self._ring.append(entry)
            self.total += 1
        if self._counter is not None:
            try:
                self._counter.inc(action=entry.action)
            except Exception:
                pass  # swallow-ok: metrics must never abort the action they describe
        if self._emit is not None:
            try:
                self._emit([(f"Control/{entry.action}", 1.0, entry.step)])
            except Exception:
                pass  # swallow-ok: monitor sinks must never abort the action they describe
        return entry

    # -- reading --------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [e.to_dict() for e in self._ring]

    def entries(self) -> List[ControlAction]:
        with self._lock:
            return list(self._ring)

    def actions(self, action: Optional[str] = None) -> List[ControlAction]:
        with self._lock:
            return [e for e in self._ring
                    if action is None or e.action == action]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def describe_action(entry: Dict[str, Any]) -> str:
    """One human line per ledger entry — shared by the doctor's
    "supervisor action" report lines and the supervisor's own logging, so
    the post-mortem reads exactly like the live log did."""
    bits = [f"step {entry.get('step')}: {entry.get('action')}"]
    if entry.get("reason"):
        bits.append(f"— {entry['reason']}")
    params = entry.get("params") or {}
    if params:
        kv = ", ".join(f"{k}={v}" for k, v in sorted(params.items()))
        bits.append(f"({kv})")
    outcome = entry.get("outcome")
    if outcome and outcome != "ok":
        bits.append(f"[{outcome}]")
    return " ".join(bits)
