"""The supervisor's rule book: live signal -> guarded knob action.

Each rule reads one signal the fleet already publishes, runs it through the
:class:`~.guard.FlapGuard` (hysteresis + cooldown + budget), and — when the
guard lets it fire — moves exactly one knob through an actuator the earlier
PRs built, recording a :class:`~.ledger.ControlAction` either way. The
table (rendered in ``docs/autotuning.md``):

======================  ==========================  =========================
signal                  condition                   action (escalation)
======================  ==========================  =========================
HealthTable straggler   any live peer > k x the     re-plan the DP-grad
verdict (PR 5)          leave-one-out median        collective around the
                                                    slow host's link
                                                    (planner re-synthesis)
dstpu_mem gauges        bytes_in_use >=             raise remat one rung;
(PR 10)                 watermark x bytes_limit     when exhausted, halve
                                                    micro-batch (2x GAS)
ServingMetrics SLA      violation rate >= r over    scale out via scale_fn
counters (PR 7)         >= n tracked finishes       when registered, else
                                                    shed (halve admission);
                                                    restore on recovery
sentinel rollbacks      >= n rollbacks within       enter the existing
(PR 4)                  the window                  degraded mode (exact
                                                    collectives)
======================  ==========================  =========================

Rules only ever *narrow* behavior toward safer/cheaper configurations
mid-run (exact collectives, more remat, less admission); re-escalation is
the operator's (``clear_degraded``) or a restart's job — an automatic
re-escalation would re-enter the very condition that triggered the rule.
"""

from typing import List, Tuple

# (rule, signal, condition knob, action, cooldown knob) — the docs table's
# machine-readable twin; tests assert the rule names the supervisor fires
# stay in sync with this book.
POLICY_TABLE: List[Tuple[str, str, str, str]] = [
    ("straggler_replan", "HealthTable straggler verdict",
     "any straggler row", "replan dp-grad around the slow link"),
    ("mem_pressure", "dstpu_mem_bytes_in_use / bytes_limit",
     "ratio >= supervisor.mem_watermark",
     "raise_remat, then halve_micro_batch"),
    ("sla_pressure", "ServingMetrics sla_violations / sla_tracked",
     ">= supervisor.sla_violation_rate over >= sla_min_tracked",
     "serving_scale (scale_fn) else serving_shed"),
    ("rollback_degrade", "sentinel rollbacks",
     ">= supervisor.rollback_threshold within rollback_window_s",
     "enter_degraded (exact collectives)"),
    ("integrity", "cross-rank fingerprint divergence (integrity tier)",
     "any unactioned divergence verdict",
     "integrity_rollback (newest VERIFIED snapshot); sticky minority -> "
     "sdc_quarantine (replan around the corrupt host)"),
]

RULE_NAMES = tuple(r[0] for r in POLICY_TABLE)


# ---------------------------------------------------------------------------
# training-side rules (sup = ControlSupervisor)
# ---------------------------------------------------------------------------


def rule_straggler(sup, step: int) -> None:
    """Straggler verdict -> re-invoke planner synthesis around the slow
    host's link. The HealthTable verdict is derived from the SHARED beacon
    table, so every controller observes the same signal at the same steps
    and the re-resolved decision still rides the planner's rank-0
    broadcast — the fleet re-plans together, not rank by rank.

    Static feasibility is checked BEFORE the guard: an engine that can
    never re-plan (planner off, ZeRO>0's declarative reductions, a
    single-axis dp span) gets one explanatory ledger note and never
    charges the global action budget with guaranteed no-ops."""
    stragglers = sup.straggler_rows()
    if not stragglers:
        # the steady-state path: one clear observation for the latch,
        # nothing else computed (feasibility probes cost planner/topo
        # lookups that do not belong on the per-step hot path)
        sup.guard.should_fire("straggler_replan", False)
        return
    axes = sup.slow_link_axes()
    if not axes or not sup.can_replan():
        if stragglers:
            ranks = sorted(r for r, _ in stragglers)
            sig = f"straggler rank(s) {ranks}"
            if not axes:
                sup.note_infeasible(
                    "straggler_replan", "straggler_replan", step=step,
                    signal=sig,
                    reason="no re-routable mesh axis (single-axis dp "
                           "span: every peer shares the link)",
                    outcome="skipped:no-slow-axes")
            else:
                sup.note_infeasible(
                    "straggler_replan", "straggler_replan", step=step,
                    signal=sig,
                    reason="planner off, or this engine has no "
                           "re-plannable DP-grad site (ZeRO>0 / "
                           "model-parallel reductions are declarative)",
                    outcome="skipped:no-replannable-site")
        return
    if not sup.guard.should_fire("straggler_replan", bool(stragglers)):
        return
    ranks = sorted(r for r, _ in stragglers)
    ratio = max(x for _, x in stragglers)
    sig = (f"straggler rank(s) {ranks} at {ratio:.1f}x the "
           f"leave-one-out peer median")
    penalty = max(float(sup.cfg.supervisor.straggler_penalty), ratio)
    summary = sup.engine.replan_dp_grad(axes, penalty=penalty)
    if summary is None:  # raced a config change between check and act
        sup.ledger.record("straggler_replan", step=step, signal=sig,
                          reason="re-plan refused by the engine",
                          outcome="skipped:no-replannable-site")
        return
    sup.ledger.record(
        "straggler_replan", step=step, signal=sig,
        reason=f"re-planned the DP-grad collective around link "
               f"axes {list(axes)}",
        params={"axes": list(axes), "penalty": round(penalty, 2),
                "plan": summary, "ranks": ranks})


def rule_memory(sup, step: int) -> None:
    """Memory gauge near ``bytes_limit`` -> raise remat; when the remat
    ladder is exhausted, halve the micro-batch (GAS doubles — the global
    batch and the training math are unchanged, per-microbatch activation
    residency halves).

    Each escalation stage is its OWN guard rule (``mem_pressure:<stage>``,
    the stage counter advancing on every successful actuation): a firing
    latches only its stage, so *sustained* pressure — the gauge never
    dropping below the watermark because the last action freed too little
    — escalates to the next rung after another ``trigger_streak`` asserted
    observations instead of latching the whole rule forever. A statically
    exhausted ladder (nothing left to actuate) is one explanatory ledger
    note, never a budget-charging no-op loop."""
    mem = sup.mem_sample() or {}
    in_use, limit = mem.get("bytes_in_use"), mem.get("bytes_limit")
    wm = float(sup.cfg.supervisor.mem_watermark)
    asserted = bool(in_use and limit and in_use >= wm * limit)
    engine = sup.engine
    # static feasibility BEFORE the guard: pressure with nothing left to
    # actuate is one explanatory note, never a budget-charging no-op loop
    can_remat = getattr(engine, "_remat_policy", None) != "nothing_saveable"
    mbs = int(getattr(engine, "micro_batch_size", 0) or 0)
    can_halve = (getattr(engine, "_train_dataloader", None) is None
                 and mbs >= 2 and mbs % 2 == 0)
    if asserted and not (can_remat or can_halve):
        frac = in_use / limit
        sig = f"mem gauge hit {frac:.2f}x bytes_limit (watermark {wm:g})"
        if getattr(engine, "_train_dataloader", None) is not None:
            # a built dataloader yields fixed-size micro batches; halving
            # the engine's micro size without reshaping the stream would
            # feed doubled draws, not smaller ones — leave the shape alone
            sup.note_infeasible(
                "halve_micro_batch", "mem_pressure", step=step, signal=sig,
                reason="remat exhausted; the training dataloader owns the "
                       "batch shape", outcome="skipped:dataloader")
        else:
            sup.note_infeasible(
                "halve_micro_batch", "mem_pressure", step=step, signal=sig,
                reason="remat ladder and micro-batch both exhausted — "
                       "operator attention needed",
                outcome="skipped:exhausted")
        return
    rule = f"mem_pressure:{sup._mem_stage}"
    if not sup.guard.should_fire(rule, asserted):
        return
    frac = in_use / limit
    sig = f"mem gauge hit {frac:.2f}x bytes_limit (watermark {wm:g})"
    policy = engine.raise_remat()
    if policy is not None:
        sup._mem_stage += 1
        sup.ledger.record(
            "raise_remat", step=step, rule=rule, signal=sig,
            reason=f"raised remat to {policy} after {sig}",
            params={"policy": policy, "frac": round(frac, 3)})
        return
    if engine.halve_micro_batch():
        sup._mem_stage += 1
        sup.ledger.record(
            "halve_micro_batch", step=step, rule=rule, signal=sig,
            reason=f"halved micro-batch to {engine.micro_batch_size} "
                   f"(gas {engine.gas}) after {sig}",
            params={"micro_batch": engine.micro_batch_size,
                    "gas": engine.gas})
    else:  # raced a structural change between check and act
        sup.ledger.record(
            "halve_micro_batch", step=step, rule=rule, signal=sig,
            reason="nothing left to actuate", outcome="skipped:exhausted")


def rule_rollbacks(sup, step: int) -> None:
    """Repeated sentinel rollbacks -> the existing degraded-mode entry
    (exact XLA collectives). Complements ``degraded_mode``'s own built-in
    trigger: the control path runs with its OWN threshold/guard so fleets
    that enable control but not the resilience-side auto-degrade still
    converge to exact transports under repeated divergence."""
    sc = sup.cfg.supervisor
    recent = sup.recent_rollbacks(sc.rollback_window_s)
    asserted = len(recent) >= int(sc.rollback_threshold)
    if not sup.guard.should_fire("rollback_degrade", asserted):
        return
    sig = (f"{len(recent)} sentinel rollback(s) within "
           f"{sc.rollback_window_s:g}s")
    rz = getattr(sup.engine, "resilience", None)
    if rz is None:
        sup.ledger.record("enter_degraded", step=step,
                          rule="rollback_degrade", signal=sig,
                          reason="no resilience manager to degrade",
                          outcome="skipped:no-resilience")
        return
    if rz.degraded:
        sup.ledger.record("enter_degraded", step=step,
                          rule="rollback_degrade", signal=sig,
                          reason="already in degraded mode",
                          outcome="skipped:already-degraded")
        return
    rz.enter_degraded(reason=f"control: {sig}")
    sup.ledger.record(
        "enter_degraded", step=step, rule="rollback_degrade", signal=sig,
        reason=f"fell back to exact collectives after {sig}")


def rule_integrity(sup, step: int) -> None:
    """Fingerprint-divergence verdicts (ISSUE 20 integrity tier) ->
    rollback to the newest VERIFIED snapshot; a ``sticky`` minority rank is
    additionally quarantined — ledger-recorded as ``sdc_quarantine`` and,
    when the planner can, the DP-grad collective is re-planned around it
    (the straggler re-plan actuator: a corrupt host and a slow host both
    need traffic routed away). Transient flips only roll back: the host is
    fine, the state is not. The verdict queue is drained ONLY when the
    guard fires, so hysteresis sees a steady asserted signal, and a
    rollback clears the queue either way (restored state moots stale
    verdicts)."""
    rz = getattr(sup.engine, "resilience", None)
    mon = getattr(rz, "integrity", None) if rz is not None else None
    if mon is None:
        return  # integrity off: not even a clear observation to feed
    verdicts = mon.pending_verdicts()
    if not sup.guard.should_fire("integrity", bool(verdicts)):
        return
    verdicts = mon.drain_verdicts()
    if not verdicts:  # raced note_rollback
        return
    steps = sorted(v["step"] for v in verdicts)
    sticky = sorted({r for v in verdicts if v.get("verdict") == "sticky"
                     for r in v.get("minority", ())})
    kinds = sorted({str(v.get("verdict")) for v in verdicts})
    sig = (f"fingerprint divergence at step(s) {steps}, "
           f"verdict(s) {kinds}, minority "
           f"{sorted({r for v in verdicts for r in v.get('minority', ())})}")
    ic = rz.cfg.integrity
    if sticky and ic.quarantine:
        fresh = [r for r in sticky if r not in mon.quarantined]
        mon.quarantined.extend(fresh)
        axes = sup.slow_link_axes()
        replanned = None
        if axes and sup.can_replan():
            replanned = sup.engine.replan_dp_grad(
                axes, penalty=float(sup.cfg.supervisor.straggler_penalty))
        sup.ledger.record(
            "sdc_quarantine", step=step, rule="integrity", signal=sig,
            reason=f"quarantined sticky-SDC rank(s) {sticky}: shadow "
                   "replay reproduced the corruption, so the host — not "
                   "the state — is bad; routed collectives around it "
                   + ("(re-planned)" if replanned else
                      "(no re-plannable site; demotion recorded for the "
                      "scheduler/operator)"),
            params={"ranks": sticky, "steps": steps,
                    "replanned": bool(replanned)})
    if ic.rollback:
        ok = rz.integrity_rollback()
        sup.ledger.record(
            "integrity_rollback", step=step, rule="integrity", signal=sig,
            reason=("restored the newest verified snapshot (corrupt state "
                    "discarded)" if ok else
                    "no verified snapshot available — training continues "
                    "on suspect state, loudly"),
            params={"steps": steps,
                    "max_step": mon.last_clean_step},
            outcome="ok" if ok else "skipped:no-verified-snapshot")
    else:
        sup.ledger.record(
            "integrity_detected", step=step, rule="integrity", signal=sig,
            reason="integrity.rollback disabled — verdict recorded only",
            params={"steps": steps}, outcome="skipped:rollback-disabled")


# ---------------------------------------------------------------------------
# serving-side rule (called from the LLMServer engine thread)
# ---------------------------------------------------------------------------


def rule_sla(sup, server) -> None:
    """Repeated SLA violations -> scale out (registered ``scale_fn``) or
    shed load (halve this replica's admission); violation rate recovering
    restores full admission. Per-replica guard rules: one hot replica must
    not shed its healthy peers."""
    sc = sup.cfg.supervisor
    m = server.metrics
    sid = int(server.replica_id)
    dv, dt = sup.sla_delta(sid, m.sla_violations, m.sla_tracked)
    rate = (dv / dt) if dt > 0 else 0.0
    asserted = dt >= int(sc.sla_min_tracked) and \
        rate >= float(sc.sla_violation_rate)
    step = server._steps
    rule = f"sla_pressure:{sid}"
    if sup.guard.should_fire(rule, asserted):
        sig = (f"replica {sid}: {dv}/{dt} SLA violations since last "
               f"tick ({rate:.0%})")
        if sup.scale_fn is not None:
            try:
                added = sup.scale_fn(sup)
                sup.ledger.record(
                    "serving_scale", step=step, rule=rule, signal=sig,
                    reason="scaled out via the registered scale_fn",
                    params={"added": str(added), "replica": sid})
                return
            except Exception as e:  # fall through to shedding
                sup.ledger.record(
                    "serving_scale", step=step, rule=rule, signal=sig,
                    reason="scale_fn raised; falling back to shedding",
                    outcome=f"failed:{type(e).__name__}")
        current = server.control_max_queue or server._ingress.maxsize
        new = max(1, int(current) // 2)
        server.control_max_queue = new
        sup.ledger.record(
            "serving_shed", step=step, rule=rule, signal=sig,
            reason=f"halved admission to {new} queued request(s)",
            params={"max_queue": new, "replica": sid})
        return
    if server.control_max_queue is not None and sup.guard.should_fire(
            f"sla_recovered:{sid}",
            dt >= int(sc.sla_min_tracked)
            and rate < float(sc.sla_violation_rate) / 2,
            restorative=True):  # un-shedding never consults the budget: an
        # exhausted budget must not pin a recovered replica at 1 request
        server.control_max_queue = None
        sup.ledger.record(
            "serving_unshed", step=step, rule=f"sla_recovered:{sid}",
            signal=f"replica {sid}: violation rate {rate:.0%}",
            reason="SLA recovered; restored full admission",
            params={"replica": sid})
