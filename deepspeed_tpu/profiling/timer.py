"""Wall-clock timers.

Reference: ``SynchronizedWallClockTimer`` (``utils/timer.py:44``) uses CUDA
events per timer; here each ``stop()`` drains XLA's async dispatch once
(``block_until_ready``) so the measured span covers device work, and
``ThroughputTimer`` (``utils/timer.py:199``) reports samples/sec + TFLOPs.
"""

import time
from typing import Dict, List, Optional

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


# one device sentinel, created on first use and reused: the previous
# implementation issued a fresh jax.device_put H2D transfer on EVERY
# stop(sync=True) — a per-step allocation + transfer on remote-attached
# TPUs just to drain the dispatch queue. The chained +0 is what forces the
# queue to retire; the operand can be the same buffer every time.
_SYNC_SENTINEL = None


def _sync():
    global _SYNC_SENTINEL
    try:
        import jax

        for _ in range(2):  # one retry with a fresh sentinel (backend reset)
            if _SYNC_SENTINEL is None:
                _SYNC_SENTINEL = jax.device_put(0)
            try:
                (_SYNC_SENTINEL + 0).block_until_ready()
                return
            except Exception:
                _SYNC_SENTINEL = None
    except Exception:
        pass


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self.elapsed_records: List[float] = []

    def start(self, sync: bool = False):
        if sync:
            _sync()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, sync: bool = True, record: bool = True):
        if not self.started:
            return
        if sync:
            _sync()
        dt = time.perf_counter() - self._start
        if record:
            self.elapsed_records.append(dt)
        self.started = False

    def elapsed(self, reset: bool = True) -> float:
        total = sum(self.elapsed_records)
        if reset:
            self.reset()
        return total

    def mean(self) -> float:
        return sum(self.elapsed_records) / max(1, len(self.elapsed_records))

    def reset(self):
        self.elapsed_records = []
        self.started = False


class SynchronizedWallClockTimer:
    """Named-timer registry (reference ``utils/timer.py:44``)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage() -> str:
        from ..accelerator import get_accelerator

        acc = get_accelerator()
        mb = 1024 * 1024
        try:
            return (f"alloc={acc.memory_allocated() / mb:.1f}MB "
                    f"peak={acc.max_memory_allocated() / mb:.1f}MB")
        except Exception:
            return "alloc=? peak=?"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False):
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        from ..utils.logging import log_dist

        msg = "time (ms) | " + " | ".join(parts)
        if memory_breakdown:
            msg += " | " + self.memory_usage()
        log_dist(msg)


class ThroughputTimer:
    """Samples/sec + TFLOPs estimate (reference ``utils/timer.py:199``)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: Optional[int] = None, monitor_memory: bool = False):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._start = None

    def start(self):
        self._start = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True,
             model_flops: Optional[float] = None):
        if self._start is None:
            return
        _sync()
        dt = time.perf_counter() - self._start
        self._start = None
        if global_step:
            self.global_step_count += 1
        if self.global_step_count <= self.start_step:
            return
        self.total_elapsed_time += dt
        self.step_elapsed_time += dt
        if (report_speed and self.steps_per_output
                and self.global_step_count % self.steps_per_output == 0):
            from ..utils.logging import log_dist

            msg = (f"step={self.global_step_count} "
                   f"samples/sec={self.avg_samples_per_sec():.2f} "
                   f"step_time={dt:.3f}s")
            if model_flops:
                msg += f" TFLOPs={model_flops / dt / 1e12:.2f}"
            log_dist(msg)
            self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        steps = self.global_step_count - self.start_step
        if steps <= 0 or self.total_elapsed_time == 0:
            return 0.0
        return steps * self.batch_size / self.total_elapsed_time
