"""Hardware trace capture (xplane/perfetto) around training steps.

Reference: DeepSpeed integrates torch.profiler via the ``flops_profiler`` and
monitor hooks; on TPU the native tool is ``jax.profiler`` — the captured
xplane protobuf opens in TensorBoard's profile plugin / Perfetto and shows
per-op device timelines, HBM traffic, and collective overlap (the evidence
trail for e.g. Domino's overlap claim on real hardware).

Usage::

    from deepspeed_tpu.profiling import trace
    with trace.capture("/tmp/tb"):          # or engine-driven below
        engine.train_batch(batch)

    trace.profile_steps(engine, batches, log_dir="/tmp/tb", steps=3)
"""

import contextlib
import os
from typing import Any, Iterable, Optional

import jax


@contextlib.contextmanager
def capture(log_dir: str, *, host_tracer_level: int = 2,
            python_tracer_level: int = 0):
    """Context manager around any block of dispatches. The trace lands in
    ``<log_dir>/plugins/profile/<run>/`` (TensorBoard layout)."""
    os.makedirs(log_dir, exist_ok=True)
    try:
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
        options.python_tracer_level = python_tracer_level
        jax.profiler.start_trace(log_dir, profiler_options=options)
    except (AttributeError, TypeError):  # older jax: no ProfileOptions /
        jax.profiler.start_trace(log_dir)  # no profiler_options kwarg
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def profile_steps(engine: Any, batches: Iterable, *, log_dir: str,
                  steps: int = 3, warmup: int = 1) -> str:
    """Run ``warmup`` uncaptured steps (compile outside the trace), then
    capture ``steps`` steps. Returns the log dir."""
    batches = list(batches)
    if not batches:
        raise ValueError("profile_steps needs at least one batch")
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    loss = None
    for i in range(warmup):
        loss = engine.train_batch(batches[i % len(batches)])
    if loss is not None:
        float(loss)  # drain so compile noise stays out of the capture
    with capture(log_dir):
        for i in range(steps):
            loss = engine.train_batch(batches[i % len(batches)])
        float(loss)  # the trace must include the real device work
    return log_dir


def annotate(name: str):
    """Named region in the trace (``jax.profiler.TraceAnnotation``)."""
    return jax.profiler.TraceAnnotation(name)


def export_spans(log_dir: str, filename: str = None) -> Optional[str]:
    """Export the telemetry span tracer's host-phase timeline
    (``telemetry/spans.py``) as Chrome-trace JSON into ``log_dir`` — the
    same directory a :func:`capture` writes its device xplane to, so the
    host step phases and the device op timeline open side by side in
    Perfetto. Returns the path, or None when the tracer holds nothing."""
    from ..telemetry.spans import export_chrome, get_tracer

    tr = get_tracer()
    spans = tr.snapshot()
    open_spans = tr.open_spans()
    if not spans and not open_spans:
        return None
    os.makedirs(log_dir, exist_ok=True)
    name = filename or f"spans-{os.getpid()}.trace.json"
    return export_chrome(os.path.join(log_dir, name), spans, open_spans)
