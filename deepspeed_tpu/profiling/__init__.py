from .flops_profiler import (FlopsProfiler, count_flops, get_model_profile,
                             params_count, xla_cost_analysis)
from . import trace
from .timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = [
    "FlopsProfiler", "count_flops", "get_model_profile", "params_count",
    "xla_cost_analysis", "SynchronizedWallClockTimer", "ThroughputTimer",
]
