"""FLOPS profiler.

Reference: ``FlopsProfiler`` (``profiling/flops_profiler/profiler.py:29``)
monkey-patches ``torch.nn.functional`` with flop-counting shims and prints a
per-module latency/FLOPs/params tree. The TPU-native design needs no patching:
a traced jaxpr *is* the op graph, so we

  1. walk the jaxpr and count FLOPs analytically per primitive (dot_general,
     conv, elementwise, reductions), descending into pjit/scan/cond/remat with
     correct trip-count multipliers, and
  2. aggregate per ``jax.named_scope`` frame — the module tree — giving the
     same depth-limited breakdown the reference prints, plus
  3. optionally cross-check against XLA's own compiled ``cost_analysis()``.
"""

from collections import defaultdict
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax import core as jcore

from ..analysis import jaxpr_walk as jw


def _size(aval) -> int:
    try:
        return int(np.prod(aval.shape))
    except Exception:
        return 0


def _dot_general_flops(eqn) -> int:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = int(np.prod([lhs.shape[d] for d in lb])) if lb else 1
    k = int(np.prod([lhs.shape[d] for d in lc])) if lc else 1
    m = int(np.prod([lhs.shape[d] for d in range(len(lhs.shape))
                     if d not in lc and d not in lb]))
    n = int(np.prod([rhs.shape[d] for d in range(len(rhs.shape))
                     if d not in rc and d not in rb]))
    return 2 * batch * m * n * k


def _conv_flops(eqn) -> int:
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    groups = eqn.params.get("feature_group_count", 1)
    kernel_elems = int(np.prod(rhs.shape)) // max(1, groups)
    # per output element: one MAC per (kernel spatial x in-channels/group)
    out_elems = _size(out)
    in_ch_factor = kernel_elems // max(1, rhs.shape[dn.rhs_spec[0]])
    return 2 * out_elems * in_ch_factor


_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "pow", "exp", "log", "tanh",
    "logistic", "rsqrt", "sqrt", "neg", "abs", "sign", "floor", "ceil",
    "erf", "erf_inv", "expm1", "log1p", "sin", "cos", "integer_pow",
    "add_any", "and", "or", "xor", "not", "select_n", "clamp", "nextafter",
    "rem", "atan2", "cbrt", "square",
}
_REDUCTION = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
              "reduce_and", "reduce_or", "argmax", "argmin", "reduce_precision",
              "cumsum", "cummax", "cummin", "cumprod"}
_FREE = {"broadcast_in_dim", "reshape", "transpose", "squeeze", "slice",
         "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
         "convert_element_type", "bitcast_convert_type", "gather", "scatter",
         "scatter-add", "rev", "iota", "copy", "device_put", "stop_gradient",
         "eq", "ne", "lt", "le", "gt", "ge", "is_finite", "sharding_constraint"}


# call-like primitives that sometimes carry no discoverable jaxpr in their
# params (custom_lin holds a bare callable): they dispatch work counted
# elsewhere, so they must cost 0, not fall through to the size estimate
_CALL_LIKE = {"pjit", "closed_call", "core_call", "custom_jvp_call",
              "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
              "checkpoint", "custom_lin", "c_jit"}


def _leaf_flops(eqn) -> int:
    """Analytic FLOPs for one leaf primitive (no sub-jaxpr)."""
    prim = eqn.primitive.name
    if prim in _CALL_LIKE:
        return 0
    if prim == "dot_general":
        return _dot_general_flops(eqn)
    if prim == "conv_general_dilated":
        return _conv_flops(eqn)
    if prim in _ELEMENTWISE:
        return _size(eqn.outvars[0].aval)
    if prim in _REDUCTION:
        return _size(eqn.invars[0].aval)
    if prim in ("psum", "all_gather", "reduce_scatter", "all_to_all", "ppermute"):
        return 0  # communication, not FLOPs — the comms logger ledgers these
    if prim in _FREE:
        return 0
    return _size(eqn.outvars[0].aval) if eqn.outvars else 0


def _jaxpr_flops(jaxpr, scope_acc, scope: str, mult: int) -> int:
    """Walk the program on the shared driver (``analysis/jaxpr_walk``):
    named-scope frames, pjit-name scope nesting, and ``scan`` trip-count
    multipliers all come from :func:`jw.walk`/:func:`jw.subjaxprs`.  The
    two FLOP-specific recursion rules stay here via the HANDLED protocol:
    ``while`` counts ONE body iteration (trip count is dynamic —
    documented caveat; the loop predicate is never counted), and ``cond``
    counts only its most expensive branch, in total AND per-scope."""
    total = [0]

    def visit(eqn, ctx):
        prim = eqn.primitive.name
        if prim == "while":
            inner = eqn.params["body_jaxpr"]
            total[0] += _jaxpr_flops(inner.jaxpr, scope_acc,
                                     f"{ctx.scope}/while", ctx.mult)
            return jw.HANDLED
        if prim == "cond":
            best_total, best_acc = 0, {}
            for b in eqn.params["branches"]:
                acc = defaultdict(int)
                t = _jaxpr_flops(b.jaxpr, acc, f"{ctx.scope}/cond", ctx.mult)
                if t >= best_total:
                    best_total, best_acc = t, acc
            for k, v in best_acc.items():
                scope_acc[k] += v
            total[0] += best_total
            return jw.HANDLED
        if jw.subjaxprs(eqn):
            # call-like (pjit/remat/custom_vjp/scan): the eqn itself costs
            # nothing; the driver recurses with scope + trip multipliers
            return None
        f = _leaf_flops(eqn) * ctx.mult
        scope_acc[ctx.scope or "<top>"] += f
        total[0] += f
        return None

    jw.walk(jaxpr, visit, scope=scope, mult=mult)
    return total[0]


def count_flops(fn: Callable, *args, **kwargs) -> Tuple[int, Dict[str, int]]:
    """Analytic FLOP count of ``fn(*args)`` plus a per-named-scope breakdown."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    scope_acc: Dict[str, int] = defaultdict(int)
    total = _jaxpr_flops(closed.jaxpr, scope_acc, "", 1)
    return total, dict(scope_acc)


def params_count(params: Any) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params)
               if hasattr(x, "shape"))


def xla_cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """XLA's own post-optimization cost model (flops, bytes accessed)."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def number_to_string(num, units=None, precision=2):
    for scale, unit in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if (units is None and abs(num) >= scale) or units == unit:
            return f"{num / scale:.{precision}f} {unit}"
    return f"{num:.{precision}f}"


def flops_to_string(flops, units=None, precision=2):
    return number_to_string(flops, units, precision) + "FLOPs"


def params_to_string(n, units=None, precision=2):
    return number_to_string(n, units, precision)


class FlopsProfiler:
    """Engine-attached profiler (reference ``profiling/flops_profiler/
    profiler.py:29``; engine hook at ``engine.py:1877`` fires on
    ``profile_step``)."""

    def __init__(self, config=None):
        self.config = config
        self.total_flops = 0
        self.scopes: Dict[str, int] = {}
        self.total_params = 0
        self.step_time = 0.0

    def profile(self, fn: Callable, args: tuple, params: Any = None,
                step_time: float = 0.0):
        self.total_flops, self.scopes = count_flops(fn, *args)
        self.total_params = params_count(params) if params is not None else 0
        self.step_time = step_time
        return self.total_flops

    def get_total_flops(self, as_string=False):
        return flops_to_string(self.total_flops) if as_string else self.total_flops

    def get_total_params(self, as_string=False):
        return params_to_string(self.total_params) if as_string else self.total_params

    def print_model_profile(self, depth: int = -1, top_modules: int = 3,
                            output_file: Optional[str] = None):
        import sys

        out = open(output_file, "w") if output_file else sys.stdout
        print("-" * 60, file=out)
        print("DeepSpeed-TPU Flops Profiler", file=out)
        print(f"params:               {params_to_string(self.total_params)}", file=out)
        print(f"fwd (+bwd) FLOPs:     {flops_to_string(self.total_flops)}", file=out)
        if self.step_time > 0:
            print(f"step latency:         {self.step_time * 1e3:.2f} ms", file=out)
            print(f"achieved throughput:  "
                  f"{flops_to_string(self.total_flops / self.step_time)}/s", file=out)
        items = sorted(self.scopes.items(), key=lambda kv: -kv[1])
        print("per-scope breakdown (named_scope tree):", file=out)
        limit = top_modules if top_modules and top_modules > 0 else len(items)
        shown = 0
        for scope, f in items:
            d = scope.count("/") + 1
            if depth != -1 and d > depth:
                continue
            if f == 0:
                continue
            print(f"  {scope or '<top>'}: {flops_to_string(f)} "
                  f"({100.0 * f / max(1, self.total_flops):.1f}%)", file=out)
            shown += 1
            if shown >= limit:
                break
        print("-" * 60, file=out)
        if output_file:
            out.close()


def get_model_profile(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
                      params: Any = None, print_profile: bool = True,
                      as_string: bool = True):
    """One-shot API (reference ``get_model_profile``): returns
    ``(flops, macs, params)``."""
    prof = FlopsProfiler()
    prof.profile(lambda *a: fn(*a, **(kwargs or {})), args, params=params)
    if print_profile:
        prof.print_model_profile()
    flops = prof.get_total_flops(as_string)
    macs = (flops_to_string(prof.total_flops // 2) if as_string
            else prof.total_flops // 2)
    nparams = prof.get_total_params(as_string)
    return flops, macs, nparams
